"""Continuous-batching request scheduler over the ragged serving step.

The engine turns the repo's serving ingredients (paged int8 KV pools,
the EP-MoE decode step, the ragged paged-attention kernel) into a
traffic-serving runtime: requests arrive on a trace, are ADMITTED into
slots when the page pool can hold their first chunk, their prompts are
prefilled in CHUNKS interleaved with other requests' decode tokens
(one ragged mixed batch per step — no prefill stall, no rectangle),
and when the pool runs dry mid-decode the lowest-priority request is
EVICTED (pages freed, request re-queued; on re-admission its prompt
*plus everything generated so far* is re-prefilled, so generation
resumes from the exact cursor — the recompute-eviction discipline).

Scheduling model (all host-side, numpy; the device work is ONE jitted
``Transformer.serving_step`` per engine step):

* a step's batch is assembled slot-by-slot under a static
  ``token_budget``: each active slot contributes
  ``min(chunk, remaining_sequence)`` tokens — 1 in steady decode, up
  to ``chunk`` while prefilling — packed at 8-aligned offsets;
* pages for the new tokens are allocated from one shared free list;
  allocation failure triggers eviction (victims: the latest-arrived
  active request not already in this step's batch — LIFO preemption),
  and a row that still cannot get pages is deferred one step;
* per-slot device ``kv_lens`` are zeroed for slots outside the batch,
  so the kernel never walks a deferred row's pages.

Degradation: the first device failure of the Pallas kernel path flips
the engine onto the XLA twin (``use_pallas=False``) and retries — the
``tools/native``-style graceful-degradation story at engine level, so
a fault-plan replay (bench.py --dryrun --faults) exercises scheduling
under chaos without hardware. Degradation is no longer one-way: every
failure also lands in a :class:`~triton_distributed_tpu.runtime.health
.HealthLedger`, whose probation machinery re-promotes the fused path
after enough clean XLA steps plus seeded probes (and, in the
disaggregated engine, re-promotes the DCN wire and fails a dead slice
over onto the survivor).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np


#: Priority classes, best-first: admission, eviction-victim selection
#: and the fleet's brownout shedding all order by the index in this
#: tuple (``tier_rank``) — interactive outranks batch outranks
#: background everywhere a scheduling decision is made.
TIERS = ("interactive", "batch", "background")

TIER_RANK = {name: i for i, name in enumerate(TIERS)}


def tier_rank(priority: str | None) -> int:
    """Numeric rank of a priority class (lower = more important).
    Unknown/unset priorities rank as interactive — the single-tenant
    default must behave exactly like the pre-tenancy engine."""
    return TIER_RANK.get(priority, 0)


@dataclass(frozen=True)
class TenantConfig:
    """Per-tenant serving contract (docs/SERVING.md § Multi-tenant).

    ``priority`` — the tenant's tier (``TIERS``), the default for its
    requests' ``Request.priority``. ``slo_ms`` — per-request modeled
    completion SLO; the fleet router's deadline slack term is
    ``slo_ms − modeled completion`` (inf = no deadline, the slack term
    vanishes). ``token_budget`` — cap on the packed tokens the
    tenant's RESIDENT rows may claim per engine step (None =
    unbounded). ``page_share`` — fraction of each engine's page pool
    the tenant's residents may hold; both shares are enforced at
    admission (a request over its tenant's share defers WITHOUT
    head-of-line blocking other tenants)."""

    priority: str = "interactive"
    slo_ms: float = float("inf")
    token_budget: int | None = None
    page_share: float = 1.0

    def __post_init__(self):
        if self.priority not in TIERS:
            raise ValueError(
                f"unknown priority {self.priority!r} (want one of "
                f"{TIERS})")
        if not 0.0 < self.page_share <= 1.0:
            raise ValueError(
                f"page_share must be in (0, 1], got {self.page_share}")
        if self.token_budget is not None and self.token_budget < 8:
            raise ValueError(
                f"token_budget must be >= 8 (one packed row), got "
                f"{self.token_budget}")


#: The tenant every unconfigured request belongs to: interactive tier,
#: no deadline, full shares — byte-identical scheduling to the
#: pre-tenancy engine.
DEFAULT_TENANT = TenantConfig()


def effective_rank(req, now: float, aging_ticks: int) -> int:
    """The rank admission actually orders by: the request's tier rank
    minus one bump per ``aging_ticks`` ticks waited since arrival —
    the anti-starvation aging that lets a background request outrank a
    sustained interactive flood once it has waited long enough.
    Deterministic (pure function of the tick clock), floor 0."""
    rank = tier_rank(getattr(req, "priority", None))
    if rank == 0 or aging_ticks <= 0:
        return rank
    waited = max(float(now) - float(req.arrival), 0.0)
    return max(0, rank - int(waited // aging_ticks))


@dataclass
class Request:
    """One serving request. ``arrival`` is in engine-step units (the
    deterministic clock the tests and the Poisson trace share)."""

    rid: int
    prompt: np.ndarray                 # (L,) int32 token ids
    max_new: int = 8
    arrival: float = 0.0
    # multi-tenancy: the tenant key (looked up in the engine/fleet
    # tenants map) and the priority class. ``priority=None`` defers to
    # the tenant's configured tier; both defaults reproduce the
    # single-tenant engine exactly.
    tenant: str = "default"
    priority: str | None = None

    # runtime (engine-owned)
    generated: list = field(default_factory=list)
    cursor: int = 0                    # tokens of `seq` already in KV
    slot: int | None = None
    evictions: int = 0
    done: bool = False
    completion_step: int | None = None
    # resident-but-not-schedulable: the request holds its slot and pages
    # but must not be batched or evicted — the state of a finished
    # prefill awaiting its KV ship (prefill side) and of a shipped-to
    # slot whose pages are still in flight (decode side). The
    # DisaggregatedEngine owns the flag; the colocated engine never
    # sets it.
    parked: bool = False

    @property
    def seq(self) -> np.ndarray:
        """Every known token of the sequence: prompt + generated. The
        recompute prefix after an eviction IS this — re-prefilling it
        resumes generation from the exact cursor."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)]
        )


@dataclass(frozen=True)
class EngineConfig:
    slots: int = 8                     # concurrent requests (R)
    token_budget: int = 64             # static packed tokens per step (T)
    chunk: int = 16                    # max prefill tokens per row-step
    page: int = 16
    npages: int = 64
    max_steps: int = 10_000
    # --- decode sampling (engine-side, over the per-slot logits) ---
    # temperature <= 0 keeps greedy argmax; > 0 samples the softmax of
    # logits/temperature, optionally top_k-truncated. Draws are keyed on
    # (seed, rid, tokens-generated-so-far) — NOT the step count — so a
    # request's tokens are deterministic under `seed` regardless of how
    # scheduling interleaved it (eviction replays and the disaggregated
    # split reproduce the colocated stream exactly).
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    # --- roles ---
    # prefill_only: the request "completes" (for this engine) once its
    # prompt is in KV and the FIRST token is generated — the prefill
    # half of a disaggregated deployment. The request is NOT marked
    # done; the on_complete hook decides whether its pages free.
    prefill_only: bool = False
    # prefix_cache: per-page refcounts + chain-hash page reuse
    # (serving/state.PagePool) — shared-prefix requests and re-admitted
    # evicted requests reattach resident pages instead of recomputing
    # the prefix.
    prefix_cache: bool = False
    # prefix_share: in-batch shared-prefix dedup (requires
    # prefix_cache). Batch assembly folds every batched row's frozen
    # pages onto the prefix cache's canonical page for the same chain
    # hash — one physical page run walked by many rows' block tables —
    # and marks the rows SHARED_PREFIX in the kernel's attention-
    # topology operand. Cuts page-walk DMA traffic and pool pressure on
    # motif traffic; token streams are unchanged (frozen pages with
    # equal chain hashes hold byte-identical KV by construction).
    prefix_share: bool = False


@dataclass
class EngineStats:
    step_times: list = field(default_factory=list)
    step_tokens: list = field(default_factory=list)
    step_generated: list = field(default_factory=list)
    completed: int = 0
    generated_tokens: int = 0
    prefill_tokens: int = 0
    evictions: int = 0
    deferrals: int = 0
    prefix_hits: int = 0               # pages reattached from the cache
    # --- in-batch shared-prefix dedup (EngineConfig.prefix_share) ---
    shared_prefix_rows: int = 0        # batched rows marked SHARED_PREFIX
    deduped_pages: int = 0             # duplicate pages folded onto canon
    # --- multi-tenancy (zero on single-tenant engines) ---
    preemptions: int = 0               # evictions forced by a higher tier
    tenant_preemptions: dict = field(default_factory=dict)  # tenant -> n
    fair_share_deferrals: dict = field(default_factory=dict)  # tenant -> n
    # CURRENTLY on the XLA twin (no longer a one-way latch: probation
    # re-promotion clears it — see HealthLedger)
    degraded: bool = False
    repromotions: int = 0              # probe-driven returns to the fused path
    # --- speculative decoding (serving/spec.py; zero on plain engines) ---
    spec_rows: int = 0                 # verify rows run (one per spec step)
    draft_tokens: int = 0              # draft tokens proposed into verify rows
    accepted_draft_tokens: int = 0     # drafts that matched the keyed sample
    spec_tokens_out: int = 0           # tokens EMITTED by verify rows
    rolled_back_tokens: int = 0        # rejected draft positions rewound
    # adaptive drafter k (spec.py adaptive_k=True): verify rows planned
    # at each per-request draft budget k — empty on fixed-k engines
    adaptive_k_rows: dict = field(default_factory=dict)
    # per-shape-key step-time ledger: grid-schedule traffic key
    # (slots, t_pad, hkv, g, d, page, chunk) ->
    # [count, total_ms, max_pages].
    # tune.traffic re-searches the hot keys after a run and persists
    # winners the next engine build resolves.
    shape_ledger: dict = field(default_factory=dict)

    def note_shape(self, key, ms: float, pages: int) -> None:
        """Record one step against its grid-schedule shape key."""
        ent = self.shape_ledger.setdefault(tuple(key), [0, 0.0, 0])
        ent[0] += 1
        ent[1] += float(ms)
        ent[2] = max(ent[2], int(pages))

    def hot_shape_keys(self, top: int = 4) -> list:
        """Shape keys ranked by total step time spent in them —
        the keys worth paying a schedule search for."""
        ranked = sorted(
            self.shape_ledger.items(), key=lambda kv: -kv[1][1]
        )
        return [k for k, _ in ranked[:max(0, int(top))]]

    @property
    def total_time(self) -> float:
        return float(sum(self.step_times))

    @property
    def sustained_tok_per_s(self) -> float:
        t = self.total_time
        return (sum(self.step_tokens) / t) if t > 0 else 0.0

    @property
    def goodput_tok_per_s(self) -> float:
        """GENERATED tokens of completed requests per wall second — the
        metric padding cannot inflate (prefill re-computation after an
        eviction, padded rectangle slots, and abandoned work all count
        against it)."""
        t = self.total_time
        return (self.generated_tokens / t) if t > 0 else 0.0

    @property
    def p99_step_ms(self) -> float:
        if not self.step_times:
            return 0.0
        return float(np.percentile(np.asarray(self.step_times), 99) * 1e3)

    @property
    def p50_step_ms(self) -> float:
        if not self.step_times:
            return 0.0
        return float(np.percentile(np.asarray(self.step_times), 50) * 1e3)

    @property
    def accepted_tokens_per_step(self) -> float:
        """Tokens a speculative verify row emits per engine step it
        runs in — the speculation multiplier. Every verify row emits at
        least 1 (the keyed sample that corrects the first rejected
        draft, or the bonus token after a clean sweep), so > 1.0 means
        drafts are genuinely being accepted. 0.0 on a plain engine."""
        if not self.spec_rows:
            return 0.0
        return self.spec_tokens_out / self.spec_rows

    @property
    def draft_acceptance_rate(self) -> float:
        if not self.draft_tokens:
            return 0.0
        return self.accepted_draft_tokens / self.draft_tokens

    @property
    def adaptive_k_histogram(self) -> dict:
        """k -> verify-row count under the adaptive drafter, ascending
        k — shows where the per-request budget actually settled."""
        return dict(sorted(self.adaptive_k_rows.items()))

    @property
    def decode_p99_step_ms(self) -> float:
        """p99 over the steps that generated at least one token — the
        latency a decoding request actually observes. In a colocated
        engine these steps carry interleaved prefill chunks (the
        contention disaggregation removes); in a decode-role engine
        every step qualifies."""
        ts = [
            t for t, g in zip(self.step_times, self.step_generated)
            if g > 0
        ]
        if not ts:
            return 0.0
        return float(np.percentile(np.asarray(ts), 99) * 1e3)

    @property
    def decode_p50_step_ms(self) -> float:
        """Median of the token-generating steps — the speculative
        bench's headline pair with :attr:`decode_p99_step_ms`."""
        ts = [
            t for t, g in zip(self.step_times, self.step_generated)
            if g > 0
        ]
        if not ts:
            return 0.0
        return float(np.percentile(np.asarray(ts), 50) * 1e3)


def poisson_trace(seed: int, n_requests: int, mean_interarrival: float,
                  len_lo: int, len_hi: int, max_new_lo: int,
                  max_new_hi: int, vocab: int) -> list:
    """Seeded Poisson arrival trace: exponential inter-arrival gaps (in
    engine-step units), prompt lengths ~ U[len_lo, len_hi) — the
    ISSUE-6 traffic shape (lengths ~U[S/8, 3S/4]) — and uniform
    max_new. Deterministic under ``seed``."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for i in range(n_requests):
        t += float(rng.exponential(mean_interarrival))
        ln = int(rng.integers(len_lo, max(len_hi, len_lo + 1)))
        out.append(Request(
            rid=i,
            prompt=rng.integers(0, vocab, (ln,)).astype(np.int32),
            max_new=int(rng.integers(max_new_lo, max(max_new_hi,
                                                     max_new_lo + 1))),
            arrival=t,
        ))
    return out


def _ceil8(x: int) -> int:
    return -(-x // 8) * 8


class ServingEngine:
    """The scheduler. Owns the host mirrors (free list, block table,
    lengths, cursors) and the device :class:`ServingState`; every
    :meth:`step` assembles one ragged batch and runs one jitted
    ``model.serving_step``."""

    def __init__(self, model, params, cfg: EngineConfig, *,
                 moe_state="auto", use_pallas: bool = True,
                 on_complete=None, health=None,
                 health_peer: str = "site:serving_step",
                 grid_schedule=None, tenants=None,
                 aging_ticks: int = 64, ops=None):
        import jax.numpy as jnp

        from triton_distributed_tpu.runtime.health import HealthLedger
        from triton_distributed_tpu.serving.protocol import ProtocolOps
        from triton_distributed_tpu.serving.state import PagePool

        self.model = model
        self.params = params
        self.cfg = cfg
        self.use_pallas = use_pallas
        # the protocol seam: every scheduling/pool transition runs
        # through these verbs (serving/protocol.py) — the same objects
        # analysis/servlint.py model-checks
        self.ops = ops if ops is not None else ProtocolOps()
        # every failure signal lands here; probation re-promotes the
        # fused path. A shared ledger (DisaggregatedEngine) makes one
        # role's kernel failure visible to the other.
        self.health = health if health is not None else HealthLedger(
            seed=cfg.seed)
        self.health_peer = health_peer
        self.state = model.init_serving_state(
            cfg.slots, cfg.npages, cfg.page
        )
        self._jnp = jnp
        pps = self.state.pages_per_seq
        self.table = np.full((cfg.slots, pps), -1, np.int32)
        # context-parallel decode: a model whose mesh carries a cp axis
        # stacks cp pools of cfg.npages pages each; the host allocator
        # mirrors that as cp per-shard pools behind one global page-id
        # namespace (appends route to the shard owning the logical page
        # index, matching the block-table column split the attention
        # walk shards on). cp == 1 is the plain allocator, unchanged.
        cp = getattr(model, "cp", 1)
        if cp > 1:
            from triton_distributed_tpu.serving.state import CpPagePool

            self.pool = CpPagePool(
                cp, cfg.npages, cfg.page, self.state.pages_per_shard,
                prefix_cache=cfg.prefix_cache,
            )
        else:
            self.pool = PagePool(cfg.npages, cfg.page,
                                 prefix_cache=cfg.prefix_cache)
        # hook: called (req, slot) when a request completes (or, under
        # prefill_only, finishes its prefill + first token). Return True
        # (the default behavior) to free the slot and pages; False to
        # PARK the request — slot and pages stay resident, unbatchable
        # and unevictable, until the caller releases them (the
        # DisaggregatedEngine's ship handshake).
        self.on_complete = on_complete
        self.slot_req: list = [None] * cfg.slots
        self.pending: deque = deque()      # not yet arrived (by time)
        self.waiting: deque = deque()      # arrived, not admitted
        self.stats = EngineStats()
        self.step_count = 0
        # --- multi-tenancy (all defaults reproduce the single-tenant
        # engine exactly: one implicit tenant at full shares, rank 0,
        # so preemption never finds a strictly-lower victim) ---
        self.tenants: dict = dict(tenants or {})
        self.aging_ticks = int(aging_ticks)
        # tiers the fleet brownout controller is currently squeezing:
        # their rows chunk at half budget and draft at k=1
        self.throttled_tiers: frozenset = frozenset()
        # hook: called (by_req, victim) when admission preempts a
        # lower-tier resident — the fleet wires its event log here
        self.on_preempt = None
        g = model.config.n_heads // model.config.n_kv_heads
        self._g = g
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            auto_block_q,
        )

        self._block_q_cap = auto_block_q(cfg.chunk, g)
        # the packed array carries a PARKING zone of block_q_cap tokens
        # past the budget: rows outside the batch (q_len == 0) park
        # their garbage writes there, where no valid span can be
        # clobbered by the kernel's sequential out DMAs
        self._t_pad = cfg.token_budget + self._block_q_cap
        # grid-schedule resolution (explicit > stored > default): the
        # traffic key this engine's every step lands on. A winner
        # persisted by tune.traffic after an earlier run is picked up
        # here on the next build — no search on the serving path.
        from triton_distributed_tpu.tune.schedule import (
            GRID_DEFAULT,
            resolve_schedule,
        )

        c = model.config
        # traffic key: geometry + the prefill chunk (chunking moves the
        # packed-token histogram the schedule is tuned against, so a
        # re-chunked engine is a DIFFERENT hot shape) + the speculation
        # coordinates (draft-k, spec_tree) so tune.traffic re-searches
        # hot SPECULATIVE shapes separately from plain decode at the
        # same geometry
        self._grid_key = (cfg.slots, self._t_pad, c.n_kv_heads, g,
                          c.head_dim, cfg.page, cfg.chunk) \
            + self._spec_key()
        sched = resolve_schedule(
            "flash_decode.ragged_paged", self._grid_key, (model.tp,),
            "int8" if c.kv_quant is not None else None, grid_schedule,
        )
        if getattr(sched, "kind", "ring") != "grid":
            sched = GRID_DEFAULT      # stale ring entry: ignore
        self.grid_schedule = sched
        self._n_bufs = int(sched.n_bufs)
        # tuned block_q is a FLOOR under the parking-zone cap: the
        # packed array always carries block_q_cap parking tokens, so
        # any block_q <= cap keeps garbage writes inside the zone
        self._block_q_floor = int(sched.block_q)
        # LL MoE workspaces sized to the PACKED step width (None when
        # the model has no fused-transport EP layers)
        self.moe_state = (
            model.init_decode_state(self._t_pad)
            if moe_state == "auto" else moe_state
        )
        if cfg.token_budget % 8:
            raise ValueError("token_budget must be 8-aligned")
        if cfg.chunk > cfg.token_budget:
            raise ValueError(
                f"chunk={cfg.chunk} exceeds token_budget="
                f"{cfg.token_budget}"
            )
        if cfg.prefix_share and not cfg.prefix_cache:
            raise ValueError(
                "prefix_share requires prefix_cache (the chain-hash "
                "registry IS the dedup index)"
            )
        if cp > 1 and cfg.prefix_share:
            raise ValueError(
                "prefix_share is incompatible with context-parallel "
                "decode: in-batch dedup retargets table columns to a "
                "canonical page, but under cp a logical page index is "
                "pinned to its owning shard — aliasing across rows "
                "would break the shard-ownership invariant"
            )
        if cp > 1 and self._spec_key() != (0, 0):
            raise ValueError(
                "speculative decoding is incompatible with context-"
                "parallel decode: verify-tree rows carry TREE topology "
                "descriptors, and the cp shard loop overwrites the "
                "topology row with its per-shard frontier shift"
            )

    def _spec_key(self) -> tuple:
        """Speculation coordinates appended to the grid-schedule traffic
        key: (draft-k, spec_tree width). (0, 0) on plain engines; the
        speculative engine reports its draft budget so hot speculative
        shapes tune separately."""
        return (0, 0)

    # ------------------------------------------------------------ requests

    def submit(self, req: Request) -> None:
        self.pending.append(req)

    def submit_trace(self, trace) -> None:
        for r in sorted(trace, key=lambda r: r.arrival):
            self.submit(r)

    @property
    def idle(self) -> bool:
        return (not self.pending and not self.waiting
                and all(r is None for r in self.slot_req))

    # ------------------------------------------------------------ tenancy

    def _tenant(self, req) -> TenantConfig:
        return self.tenants.get(
            getattr(req, "tenant", "default"), DEFAULT_TENANT)

    def _rank(self, req) -> int:
        """Static tier rank: the request's own priority, else its
        tenant's configured tier."""
        pr = getattr(req, "priority", None)
        if pr is None:
            pr = self._tenant(req).priority
        return tier_rank(pr)

    def _eff_rank(self, req) -> int:
        """Admission-order rank WITH anti-starvation aging."""
        pr = getattr(req, "priority", None)
        if pr is None:
            pr = self._tenant(req).priority
        rank = tier_rank(pr)
        if rank == 0 or self.aging_ticks <= 0:
            return rank
        waited = max(float(self.step_count) - float(req.arrival), 0.0)
        return max(0, rank - int(waited // self.aging_ticks))

    def _chunk_for(self, req) -> int:
        """Per-request prefill chunk: the configured budget, halved
        (floor 1) while the request's tier is under a brownout
        squeeze."""
        c = self.cfg.chunk
        if self.throttled_tiers:
            pr = getattr(req, "priority", None)
            if pr is None:
                pr = self._tenant(req).priority
            if pr in self.throttled_tiers:
                c = max(1, c // 2)
        return c

    # ----------------------------------------------------------- allocator

    def _pages_held(self, cursor: int) -> int:
        return -(-cursor // self.cfg.page)

    def _alloc(self, slot: int, held: int, need: int) -> bool:
        """Grow slot's table from ``held`` to ``need`` pages; all-or-
        nothing — :meth:`ProtocolOps.alloc`."""
        return self.ops.alloc(self, slot, held, need)

    def _free_slot(self, slot: int) -> None:
        """Release the slot's page references (the refcount
        discipline) — :meth:`ProtocolOps.free_slot`."""
        self.ops.free_slot(self, slot)

    def _evict_one(self, batched: set) -> bool:
        """Priority-aware LIFO eviction through the recompute
        discipline — :meth:`ProtocolOps.evict_one`."""
        return self.ops.evict_one(self, batched)

    def _preempt_for(self, by_req) -> bool:
        """Priority preemption of the lowest-tier resident strictly
        below ``by_req``'s effective rank —
        :meth:`ProtocolOps.preempt_for`."""
        return self.ops.preempt_for(self, by_req)

    # ---------------------------------------------------------------- step

    def _row_take_bound(self, req) -> int:
        """Upper bound on the tokens this request's next row packs —
        the admission/reservation headroom term. The speculative engine
        widens it by its draft budget."""
        return min(self._chunk_for(req), len(req.seq) - req.cursor)

    def _committed_pages(self) -> int:
        """Pages the already-admitted slots will claim for their NEXT
        chunk but have not allocated yet — admission must not promise
        them away (allocation happens at batch assembly)."""
        tot = 0
        for req in self.slot_req:
            if req is None or req.parked or req.done:
                continue
            take = self._row_take_bound(req)
            tot += max(
                self._pages_held(req.cursor + take)
                - self._pages_held(req.cursor), 0,
            )
        return tot

    def _fair_share_ok(self, req, first: int) -> bool:
        """Per-tenant fair-share admission gate: would admitting
        ``req`` push its tenant past its configured ``page_share`` of
        the pool, or past its ``token_budget`` of packed tokens per
        step (summed over the tenant's resident rows)? Tenant-local —
        a violation defers THIS request without head-of-line blocking
        other tenants."""
        tc = self._tenant(req)
        if tc.page_share >= 1.0 and tc.token_budget is None:
            return True
        tenant = getattr(req, "tenant", "default")
        resident = [
            r for r in self.slot_req
            if r is not None and not r.done
            and getattr(r, "tenant", "default") == tenant
        ]
        if tc.page_share < 1.0:
            cap = int(tc.page_share * self.pool.npages)
            held = sum(self._pages_held(r.cursor) for r in resident)
            if held + self._pages_held(first) > cap:
                return False
        if tc.token_budget is not None:
            packed = sum(self._row_take_bound(r) for r in resident
                         if not r.parked)
            if packed + first > tc.token_budget:
                return False
        return True

    def _admit(self) -> None:
        """Priority admission (effective tier rank, then FIFO; with one
        tenant every rank is 0 and this is the pre-tenancy FIFO
        exactly) — :meth:`ProtocolOps.admit`."""
        self.ops.admit(self)

    # ------------------------------------------------------ prefix cache

    def _page_hashes(self, req, upto: int) -> list:
        """Chain hashes of ``req.seq``'s first ``upto`` full pages."""
        from triton_distributed_tpu.serving.state import page_chain_hash

        seq, page = req.seq, self.cfg.page
        hashes, h = [], 0
        for p in range(upto):
            h = page_chain_hash(h, seq[p * page:(p + 1) * page])
            hashes.append(h)
        return hashes

    def _attach_prefix(self, req, slot: int) -> None:
        """Reattach the longest run of resident full pages matching this
        request's prefix; the cursor jumps past them — those tokens'
        K/V are already in the pool, byte-identical (frozen pages are a
        pure function of the chained prefix). At least one trailing
        token is always left to recompute so the admission step still
        produces the row's next-token logits."""
        page = self.cfg.page
        limit = min((len(req.seq) - 1) // page, self.state.pages_per_seq)
        matched = 0
        for h in self._page_hashes(req, limit):
            pg = self.pool.lookup(h, matched)
            if pg is None:
                break
            self.pool.retain(pg)
            self.table[slot, matched] = pg
            matched += 1
        if matched:
            req.cursor = matched * page
            self.stats.prefix_hits += matched

    def _register_frozen(self, req, slot: int, old_cursor: int) -> None:
        """Publish pages the cursor just moved past (their content is
        frozen — nothing writes below the cursor) into the prefix
        cache."""
        page = self.cfg.page
        first = old_cursor // page          # first page possibly frozen now
        last = req.cursor // page           # pages [0, last) are full
        if last <= first:
            return
        hashes = self._page_hashes(req, last)
        for p in range(first, last):
            self.pool.register(int(self.table[slot, p]), hashes[p])

    def _plan_row(self, req) -> np.ndarray:
        """The tokens this request's row packs THIS step. Base engine:
        the next ``min(chunk, remaining)`` sequence tokens. The
        speculative engine appends provisional draft tokens to steady
        decode rows (its override records which tail is draft)."""
        take = min(self._chunk_for(req), len(req.seq) - req.cursor)
        return np.asarray(req.seq[req.cursor:req.cursor + take],
                          np.int32)

    def _row_topology(self, s: int, req, take: int):
        """Per-row attention-topology descriptor (one
        ``(2+2W,)`` int32 row, kernels/ragged_paged_attention.py
        layout) for this step's batch, or None for CAUSAL — the
        default. The speculative engine returns TREE descriptors for
        packed verify trees; batch assembly may still overwrite CAUSAL
        rows with SHARED_PREFIX after the dedup pass."""
        return None

    def _dedup_shared_prefixes(self, batched, topo, width: int) -> None:
        """In-batch shared-prefix dedup (``cfg.prefix_share``): fold
        each batched row's FROZEN pages (fully below its cursor —
        nothing writes them again) onto the prefix cache's canonical
        page for the same chain hash, releasing the duplicate. Rows
        whose leading pages end up multiply-referenced are marked
        SHARED_PREFIX with ``aux = split`` tokens; the kernel masks
        them causally (aliasing is a table-level fact) but the page
        walk now hits one physical run shared across the batch."""
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            TOPO_CAUSAL,
            shared_prefix_topology_row,
        )

        page = self.cfg.page
        for s in sorted(batched):
            req = self.slot_req[s]
            frozen = min(req.cursor // page, self.state.pages_per_seq)
            if frozen <= 0:
                continue
            run = 0
            for p, h in enumerate(self._page_hashes(req, frozen)):
                pg = int(self.table[s, p])
                canon = self.pool.lookup(h, p)
                if canon is not None and canon != pg:
                    self.pool.release(pg)
                    self.pool.retain(canon)
                    self.table[s, p] = canon
                    self.stats.deduped_pages += 1
                    pg = canon
                if run == p and self.pool.refs[pg] >= 2:
                    run = p + 1
            if run > 0 and topo[s, 0] == TOPO_CAUSAL:
                topo[s] = shared_prefix_topology_row(
                    min(run * page, int(req.cursor)), width
                )
                self.stats.shared_prefix_rows += 1

    def _assemble(self):
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            causal_topologies,
            topo_width,
        )

        cfg = self.cfg
        R, T = cfg.slots, self._t_pad
        tokens = np.zeros((T,), np.int32)
        token_rows = np.zeros((T,), np.int32)
        token_pos = np.full((T,), -1, np.int32)
        # inactive slots PARK their garbage output block past the
        # budget (see __init__) — never over another row's valid span
        q_starts = np.full((R,), cfg.token_budget, np.int32)
        q_lens = np.zeros((R,), np.int32)
        kv_dev = np.zeros((R,), np.int32)
        topo_w = topo_width(self._block_q_cap)
        topo = causal_topologies(R, topo_w)
        next_start = 0
        batched: set = set()
        takes: dict = {}
        for s in range(R):
            req = self.slot_req[s]
            if req is None or req.parked or req.done:
                continue
            if len(req.seq) - req.cursor <= 0:
                continue
            row = self._plan_row(req)
            take = len(row)
            if take <= 0:
                continue
            if next_start + _ceil8(take) > cfg.token_budget:
                self.stats.deferrals += 1
                continue                   # token budget spent
            held = self._pages_held(req.cursor)
            need = self._pages_held(req.cursor + take)
            if self.ops.ensure_pages(self, s, held, need, batched):
                # allocation succeeded
                span = slice(next_start, next_start + take)
                tokens[span] = row
                token_rows[span] = s
                token_pos[span] = np.arange(
                    req.cursor, req.cursor + take, dtype=np.int32
                )
                q_starts[s] = next_start
                q_lens[s] = take
                kv_dev[s] = req.cursor + take
                next_start += _ceil8(take)
                batched.add(s)
                takes[s] = take
                desc = self._row_topology(s, req, take)
                if desc is not None:
                    topo[s] = desc
                continue
            # page allocation failed even after eviction: defer the row
            self.stats.deferrals += 1
        if cfg.prefix_share and batched:
            self._dedup_shared_prefixes(batched, topo, topo_w)
        return (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
                topo, batched, takes)

    def _step_jit(self):
        """The jitted device step this engine launches. The speculative
        engine overrides this with the all-positions-logits twin (same
        batch contract, (T, vocab) logits)."""
        return self.model._serving_jit

    def _run_device(self, arrays, block_q):
        jnp = self._jnp
        (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
         topo) = arrays
        state = self.state.replace(
            block_table=jnp.asarray(self.table),
            kv_lens=jnp.asarray(kv_dev),
            cursors=jnp.asarray(
                [0 if r is None else r.cursor for r in self.slot_req],
                dtype=jnp.int32,
            ),
        )
        from triton_distributed_tpu.lang.launch import maybe_instrument

        # host-mode heartbeat around the jitted step: an armed watchdog
        # sees a wedged serving step (site "serving_step"), and a
        # fault-plan Stall at that site gates here
        step_fn = maybe_instrument(
            self._step_jit(), axis=None, site="serving_step",
            collective_id=("serving_step", self.health_peer), n=1,
            step=self.step_count,
        )
        out = step_fn(
            self.params, state, jnp.asarray(tokens),
            jnp.asarray(token_rows), jnp.asarray(token_pos),
            jnp.asarray(q_starts), jnp.asarray(q_lens),
            jnp.asarray(topo),
            self.moe_state, block_q, self.use_pallas, self._n_bufs,
        )
        if self.moe_state is None:
            logits, self.state = out
        else:
            logits, self.state, self.moe_state = out
        return np.asarray(logits)          # host fetch = the fence

    def step(self) -> dict:
        """One engine step: admit → assemble → device step → advance
        cursors/completions. Returns a small per-step report."""
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            auto_block_q,
        )

        self._admit()
        (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
         topo, batched, takes) = self._assemble()
        report = {"step": self.step_count, "batched": len(batched),
                  "tokens": int(q_lens.sum())}
        if not batched:
            self.step_count += 1
            return report
        block_q = auto_block_q(int(q_lens.max()), self._g)
        # tuned floor (grid schedule): never past the parking-zone cap
        block_q = min(self._block_q_cap,
                      max(block_q, self._block_q_floor))
        from triton_distributed_tpu.runtime.health import PeerState

        peer = self.health_peer
        if self.use_pallas \
                and self.health.state(peer) is PeerState.UNHEALTHY:
            # the ledger condemned the fused path out-of-band (a shared
            # ledger's other role, a watchdog trip): demote before
            # launching
            self.use_pallas = False
            self.stats.degraded = True
        # PROBATION: on the seeded schedule, try the fused path again
        probing = (not self.use_pallas
                   and self.health.probe_due(peer, self.step_count))
        if probing:
            self.use_pallas = True
        t0 = time.perf_counter()
        arrays = (tokens, token_rows, token_pos, q_starts, q_lens, kv_dev,
                  topo)
        try:
            logits = self._run_device(arrays, block_q)
        except Exception:
            if not self.use_pallas:
                raise
            # degradation: fall back to the XLA twin (the op-level
            # with_fallback story at engine level) — scheduling state is
            # untouched, re-run the batch. The failure is a ledger
            # signal: a probe failure drops straight back to UNHEALTHY,
            # a first failure is fatal (kernel_error) so re-entry to the
            # fused path only ever happens through clean probes.
            if probing:
                self.health.probe_result(peer, False,
                                         step=self.step_count)
            else:
                self.health.record("kernel_error", peer,
                                   step=self.step_count)
            self.use_pallas = False
            self.stats.degraded = True
            logits = self._run_device(arrays, block_q)
        else:
            if probing:
                st = self.health.probe_result(peer, True,
                                              step=self.step_count)
                if st is PeerState.HEALTHY:
                    # enough clean probes: stay on the fused path
                    self.stats.degraded = False
                    self.stats.repromotions += 1
                else:
                    self.use_pallas = False   # keep earning probes
            elif not self.use_pallas and self.stats.degraded:
                st = self.health.observe_clean(peer,
                                               step=self.step_count)
                if st is PeerState.HEALTHY:
                    # SUSPECT cleared (non-fatal signal sources): resume
                    self.use_pallas = True
                    self.stats.degraded = False
                    self.stats.repromotions += 1
        dt = time.perf_counter() - t0
        gen_this_step = 0
        prefill_this_step = 0
        for s in sorted(batched):
            req = self.slot_req[s]
            emitted, prefill_toks = self._advance_row(
                s, req, takes[s], logits, q_starts, q_lens)
            gen_this_step += emitted
            prefill_this_step += prefill_toks
        self.stats.step_times.append(dt)
        self.stats.step_tokens.append(int(q_lens.sum()))
        self.stats.step_generated.append(gen_this_step)
        self.stats.note_shape(
            self._grid_key, dt * 1e3,
            self.pool.npages - self.pool.available,
        )
        self.stats.prefill_tokens += prefill_this_step
        report.update(
            ms=round(dt * 1e3, 3), generated=gen_this_step,
            free_pages=self.pool.available,
            waiting=len(self.waiting) + len(self.pending),
        )
        self.step_count += 1
        return report

    def _advance_row(self, s: int, req, take: int, logits,
                     q_starts, q_lens) -> tuple:
        """Advance one batched row after the device step: move the
        cursor past the packed tokens, publish newly-frozen pages, and
        sample at the sequence frontier. Returns ``(emitted,
        prefill_tokens)`` — tokens this row EMITTED into its stream and
        packed tokens that were prefill (not generation) work. The
        speculative engine overrides this with the verify/accept loop
        (multi-token emission + rejected-draft rollback)."""
        self.ops.advance_cursor(self, s, req, take)
        if req.cursor == len(req.seq):
            # the row's last packed token was its sequence frontier:
            # the logits row is the next-token distribution
            tok = self._sample(logits[s], req)
            req.generated.append(tok)
            self._maybe_complete(req, s)
            return 1, take - 1
        return 0, take

    def _maybe_complete(self, req, s: int) -> None:
        """Completion check after a row emitted into ``req.generated``
        — :meth:`ProtocolOps.complete`."""
        self.ops.complete(self, req, s)

    def _sample(self, row_logits, req) -> int:
        """Next token for one completed row. Greedy argmax at
        ``temperature <= 0``; otherwise softmax sampling of
        ``logits/temperature`` over the ``top_k`` best (0 = full vocab),
        drawn from a generator keyed on (seed, rid, generated-so-far) —
        request-local, so scheduling (chunking, eviction replays, the
        disaggregated prefill/decode split) can never change a
        request's token stream."""
        t = self.cfg.temperature
        if t <= 0.0:
            return int(np.argmax(row_logits))
        z = np.asarray(row_logits, np.float64) / t
        k = self.cfg.top_k
        if 0 < k < z.shape[-1]:
            kth = np.partition(z, -k)[-k]
            z = np.where(z >= kth, z, -np.inf)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        rng = np.random.default_rng(
            (self.cfg.seed, req.rid, len(req.generated))
        )
        return int(rng.choice(p.shape[-1], p=p))

    def run(self, trace=None, max_steps: int | None = None) -> EngineStats:
        """Drive the engine until the trace drains (or ``max_steps``)."""
        if trace is not None:
            self.submit_trace(trace)
        max_steps = max_steps or self.cfg.max_steps
        for _ in range(max_steps):
            if self.idle:
                break
            self.step()
        return self.stats

    # ------------------------------------------------ shipped admission
    # The decode half of a disaggregated deployment admits requests
    # whose KV was COMPUTED ELSEWHERE: reserve_shipped claims the slot
    # and the block-table-assigned landing pages up front (parked — the
    # in-flight-transfer state eviction must never touch), and
    # commit_shipped flips the row schedulable once the pages have
    # landed. Admission therefore gates on *shipped* pages, not on
    # promises.

    def reserve_shipped(self, req) -> tuple | None:
        """Claim a slot + landing pages for a request whose first
        ``req.cursor`` tokens of KV will arrive by transfer —
        :meth:`ProtocolOps.reserve_shipped`. Returns (slot, page_ids)
        or None (no slot / pool pressure — the caller retries, leaving
        the source pages pinned)."""
        return self.ops.reserve_shipped(self, req)

    def commit_shipped(self, req) -> None:
        """The transfer into this request's reserved pages has landed:
        the row becomes schedulable (and evictable) like any other —
        :meth:`ProtocolOps.commit_shipped`."""
        self.ops.commit_shipped(self, req)

    def release_parked(self, slot: int) -> None:
        """Free a parked slot (source-side handoff after its pages have
        shipped, or an abandoned reservation) —
        :meth:`ProtocolOps.release_parked`."""
        self.ops.release_parked(self, slot)

    # The wire-form page plumbing below is shared by every pool→pool
    # transfer this engine is an endpoint of: the disaggregated
    # prefill→decode ship and the fleet's replica→replica migration
    # (serving/fleet.py) both move the pool's NATIVE quantized bytes,
    # so a page that travels is byte-identical to one that never moved.

    def _kv_wire_jits(self) -> tuple:
        jits = getattr(self, "_kv_wire_cache", None)
        if jits is None:
            import jax

            from triton_distributed_tpu.kernels.kv_ship import (
                gather_kv_pages,
                scatter_kv_pages,
            )

            jits = (jax.jit(gather_kv_pages),
                    jax.jit(scatter_kv_pages, donate_argnums=(0,)))
            self._kv_wire_cache = jits
        return jits

    def gather_pages(self, pids) -> tuple:
        """Pull pool pages ``pids`` into the kv_ship wire layout
        (``(q, s)`` — int8 payload + f32 scale rail under
        ``kv_quant``)."""
        import jax.numpy as jnp

        gather, _ = self._kv_wire_jits()
        return gather(self.state.layers,
                      jnp.asarray(list(pids), jnp.int32))

    def land_pages(self, pids, q_payload, s_payload) -> None:
        """Scatter an arrived wire payload into this engine's pools at
        page slots ``pids`` (donating scatter + landing fence, the
        ``_commit_ships`` discipline)."""
        import jax
        import jax.numpy as jnp

        _, scatter = self._kv_wire_jits()
        new_layers = scatter(self.state.layers,
                             jnp.asarray(list(pids), jnp.int32),
                             q_payload, s_payload)
        jax.block_until_ready(new_layers)
        self.state = self.state.replace(layers=new_layers)


# ===================================================================
# Disaggregated prefill/decode: two role engines, KV shipped between
# ===================================================================

@dataclass
class ShipRecord:
    """One in-flight KV transfer (prefill pool → decode pool)."""

    req: Request
    pslot: int                   # prefill-side slot (pages pinned)
    dslot: int                   # decode-side reserved slot
    dpids: list                  # decode-side landing page ids
    payload: tuple               # (q, s) device arrays on the decode mesh
    issued_tick: int
    wire_bytes: int
    raw_bytes: int
    launch_ms: float = 0.0


@dataclass
class DisaggStats:
    """Two role engines' stats plus the ship ledger. Wall-time metrics
    model the production deployment — the roles run on DISJOINT slices,
    so the system's wall clock is the slower role, not the host-side
    sum this single-process harness serializes."""

    prefill: EngineStats
    decode: EngineStats
    ships: int = 0
    ship_ms: list = field(default_factory=list)
    shipped_wire_bytes: int = 0
    shipped_raw_bytes: int = 0
    # CURRENTLY on the XLA transfer (probation re-promotion clears it)
    degraded_transport: bool = False
    ship_retries: int = 0              # DCN attempts retried before success/fallback
    transport_repromotions: int = 0    # probe-driven returns to the DCN wire
    # --- slice-death failover ---
    failover_role: str | None = None   # which role's slice died
    failover_tick: int | None = None
    failover_requeued: int = 0         # requests re-queued onto the survivor
    failover_re_prefill_tokens: int = 0  # KV tokens that must re-prefill
    recovery_tick: int | None = None   # first tick with every re-queued req done

    @property
    def failover(self) -> dict | None:
        """The failover outcome in one dict (None if no slice died)."""
        if self.failover_role is None:
            return None
        return {
            "role": self.failover_role,
            "tick": self.failover_tick,
            "requeued": self.failover_requeued,
            "re_prefill_tokens": self.failover_re_prefill_tokens,
            "recovery_tick": self.recovery_tick,
        }

    @property
    def completed(self) -> int:
        return self.decode.completed

    @property
    def goodput_tok_per_s(self) -> float:
        t = max(self.prefill.total_time, self.decode.total_time)
        return (self.decode.generated_tokens / t) if t > 0 else 0.0

    @property
    def decode_p99_step_ms(self) -> float:
        return self.decode.decode_p99_step_ms

    @property
    def wire_compression(self) -> float:
        """Raw-payload bytes per wire byte actually shipped (> 1 means
        the quantized wire genuinely shrank the DCN transfer)."""
        return (self.shipped_raw_bytes / self.shipped_wire_bytes
                if self.shipped_wire_bytes else 1.0)


class DisaggregatedEngine:
    """Two-role serving topology: a PREFILL engine runs chunked prefill
    (plus the first token) into its local page pool; each finished
    request's KV pages then ship slice→slice — int8 page payloads with
    their per-row f32 scale planes, the pool's native quantized layout
    riding the paired-rail wire — landing in the DECODE engine's pool
    at block-table-assigned slots, overlapped with ongoing decode
    steps. The decode engine admits a request only once its pages have
    LANDED (reserve → transfer → commit), and in-flight transfers pin
    their pages on both sides, so eviction can never free a page
    mid-ship.

    Transport selection (``transport=``):

    * ``"dcn"`` — the quantized DCN wire: paired payload+scale
      ``ppermute`` rails over the hybrid mesh's DCN axis
      (:func:`runtime.multislice.dcn_wire_kv_ship`); requires
      ``hybrid_mesh``.
    * ``"xla"`` — :func:`tools.native.xla_kv_ship`: a plain device_put
      of the payload onto the decode mesh — the degradation target.
    * ``"auto"`` — ``"dcn"`` when a hybrid mesh is given, else
      ``"xla"``. The FIRST failure of the wire path degrades the
      engine onto ``"xla"`` for the rest of the session
      (``stats.degraded_transport``), mirroring the kernel→XLA-twin
      story at engine level.

    ``ship_delay_steps`` holds a transfer "in flight" for that many
    ticks before committing — on hardware the window is the real DCN
    latency; here it deterministically exercises the
    overlap/eviction-pinning machinery.

    ``placement="auto"`` consults the perf model
    (:func:`tune.perf_model.refuse_disaggregation`) with the expected
    ``traffic`` shape and REFUSES to construct the split topology when
    the KV wire would dominate the decode window it must hide under.
    """

    def __init__(self, prefill_model, prefill_params, decode_model,
                 decode_params, cfg: EngineConfig, *, decode_cfg=None,
                 hybrid_mesh=None, dcn_axis: str = "dcn",
                 transport: str = "auto", ship_delay_steps: int = 0,
                 placement: str = "force", traffic: dict | None = None,
                 moe_state="auto", use_pallas: bool = True, health=None,
                 spec_k: int = 0, drafter=None,
                 adaptive_k: bool = False):
        from dataclasses import replace as _rep

        from triton_distributed_tpu.runtime.health import HealthLedger

        if transport not in ("auto", "dcn", "xla"):
            raise ValueError(f"unknown transport {transport!r}")
        if transport == "auto":
            transport = "dcn" if hybrid_mesh is not None else "xla"
        if transport == "dcn" and hybrid_mesh is None:
            raise ValueError("transport='dcn' needs a hybrid_mesh")
        self.health = health if health is not None else HealthLedger(
            seed=cfg.seed)
        if decode_cfg is None:
            # the decode role's batches are at most one token per slot
            # (8 packed slots each — the row alignment): size its
            # packed width to 8·slots instead of the prefill budget,
            # never wider than it. Part of the point of the split: the
            # decode slice's steps stop paying prefill-sized
            # buffers/blocks (the colocated engine cannot shrink its
            # budget — its steps must carry prefill chunks). Evicted
            # requests re-prefilling decode-side chunk at this
            # narrower width.
            dbudget = max(8, min(8 * cfg.slots, cfg.token_budget))
            decode_cfg = _rep(
                cfg, token_budget=dbudget, chunk=min(cfg.chunk, dbudget),
            )
        dcfg = decode_cfg
        if dcfg.page != cfg.page:
            raise ValueError(
                f"page size must match across roles ({cfg.page} vs "
                f"{dcfg.page}) — pages ship verbatim"
            )
        if placement == "auto":
            from triton_distributed_tpu.tune import perf_model

            traffic = dict(traffic or {})
            if spec_k:
                # speculation changes the ship cadence: the decode
                # window the wire must hide under SHRINKS by the
                # accepted-tokens-per-step factor — the perf model
                # prices that (tune/perf_model.spec_step_ms)
                traffic.setdefault("spec_k", spec_k)
            reason = perf_model.refuse_disaggregation(
                decode_model.config, cfg.page, traffic or {},
                ledger=self.health,
            )
            if reason is not None:
                raise ValueError(
                    f"auto placement refuses disaggregation: {reason}"
                )
        self.transport = transport
        self._transport_pref = transport   # what we re-promote back to
        self.hybrid_mesh = hybrid_mesh
        self.dcn_axis = dcn_axis
        self.ship_delay_steps = int(ship_delay_steps)
        self.prefill = ServingEngine(
            prefill_model, prefill_params,
            _rep(cfg, prefill_only=True),
            moe_state=moe_state, use_pallas=use_pallas,
            on_complete=self._on_prefill_complete, health=self.health,
        )
        self.spec_k = int(spec_k)
        if spec_k:
            # speculation lives on the DECODE role only: the prefill
            # role emits at most one token per request (its frontier
            # draw), so there is nothing to draft there. Local import —
            # spec.py subclasses ServingEngine from this module.
            from triton_distributed_tpu.serving.spec import (
                SpeculativeEngine,
            )

            self.decode = SpeculativeEngine(
                decode_model, decode_params,
                _rep(dcfg, prefill_only=False),
                spec_k=spec_k, drafter=drafter, adaptive_k=adaptive_k,
                moe_state=moe_state, use_pallas=use_pallas,
                health=self.health,
            )
        else:
            self.decode = ServingEngine(
                decode_model, decode_params,
                _rep(dcfg, prefill_only=False),
                moe_state=moe_state, use_pallas=use_pallas,
                health=self.health,
            )
        self._ready: deque = deque()       # (req, prefill slot) awaiting ship
        self._inflight: list = []
        self._dead_role: str | None = None  # set by slice-death failover
        self._requeued: list = []           # failover's re-queued requests
        self.ticks = 0
        self.stats = DisaggStats(
            prefill=self.prefill.stats, decode=self.decode.stats
        )
        self._build_jits()

    # ------------------------------------------------------------ plumbing

    def _build_jits(self):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from triton_distributed_tpu.kernels.kv_ship import (
            gather_kv_pages,
            scatter_kv_pages,
        )

        self._gather_jit = jax.jit(gather_kv_pages)
        self._scatter_jit = jax.jit(
            scatter_kv_pages, donate_argnums=(0,)
        )
        mesh_d = self.decode.model.mesh
        tp = self.decode.model.tp_axis
        # payload (L·2, P, Hkv, page[, D]): KV heads stay sharded over
        # the decode slice's tp axis, like the pools they land in
        self._q_sharding = NamedSharding(mesh_d, P(None, None, tp))
        self._s_sharding = NamedSharding(mesh_d, P(None, None, tp))

    def _on_prefill_complete(self, req, slot) -> bool:
        """Prefill-role completion hook: requests already done (max_new
        reached during prefill) finish here; everyone else parks —
        pages pinned — until their KV has shipped."""
        if len(req.generated) >= req.max_new:
            req.done = True
            # account the finished request on the decode ledger (the
            # system's completion ledger), not the prefill engine's
            self.decode.stats.completed += 1
            self.decode.stats.generated_tokens += len(req.generated)
            return True                    # free the prefill slot now
        req.parked = True
        self._ready.append((req, slot))
        return False                       # hold pages for the ship

    # ------------------------------------------------------------ shipping

    def _launch_ships(self) -> None:
        import time as _t

        import jax.numpy as jnp

        # drain the whole ready cohort FIRST (reservations are cheap
        # bookkeeping), then ship it as ONE gather + ONE transport
        # flight: per-tick launch cost stops scaling with the number of
        # simultaneously finishing prefills, and the DCN rail flies one
        # big pair instead of a convoy of small ones
        cohort = []
        while self._ready:
            req, pslot = self._ready[0]
            res = self.decode.reserve_shipped(req)
            if res is None:
                break                      # decode backpressure; retry
            self._ready.popleft()
            dslot, dpids = res
            npg = self.prefill._pages_held(req.cursor)
            cohort.append((req, pslot, dslot, dpids, npg))
        if not cohort:
            return
        t0 = _t.perf_counter()
        pids = jnp.asarray(np.concatenate([
            self.prefill.table[pslot, :npg].astype(np.int32)
            for _, pslot, _, _, npg in cohort
        ]))
        qpay, spay = self._gather_jit(self.prefill.state.layers, pids)
        payload = self._run_transport(qpay, spay)
        dt = _t.perf_counter() - t0
        q_elems = int(np.prod(qpay.shape))
        wire = q_elems * qpay.dtype.itemsize + (
            int(np.prod(spay.shape)) * 4 if spay is not None else 0
        )
        raw = q_elems * max(2, qpay.dtype.itemsize)
        # one ShipRecord per request (the scheduling unit: pins, slots
        # and commit hooks stay per-request); bytes and launch time are
        # attributed by page share, so stats.ships keeps meaning "one
        # request's KV shipped"
        total_pg = sum(npg for *_, npg in cohort)
        for req, pslot, dslot, dpids, npg in cohort:
            frac = npg / total_pg
            self._inflight.append(ShipRecord(
                req=req, pslot=pslot, dslot=dslot, dpids=dpids,
                payload=payload, issued_tick=self.ticks,
                wire_bytes=int(round(wire * frac)),
                raw_bytes=int(round(raw * frac)),
                launch_ms=dt * 1e3 * frac,
            ))

    def _run_transport(self, qpay, spay):
        from triton_distributed_tpu.runtime.health import PeerState

        peer = "site:kv_ship"
        if (self.transport == "dcn"
                and self.health.state(peer) is PeerState.UNHEALTHY):
            # condemned out-of-band (watchdog trip on a prior ship)
            self.transport = "xla"
            self.stats.degraded_transport = True
        probing = (self._transport_pref == "dcn"
                   and self.transport == "xla"
                   and self.health.probe_due(peer, self.ticks))
        if self.transport == "dcn" or probing:
            out = self._dcn_with_retries(qpay, spay)
            if out is not None:
                if probing:
                    st = self.health.probe_result(peer, True,
                                                  step=self.ticks)
                    if st is PeerState.HEALTHY:
                        self.transport = "dcn"
                        self.stats.degraded_transport = False
                        self.stats.transport_repromotions += 1
                elif self.health.state(peer) is PeerState.UNHEALTHY:
                    # the ship completed but only because a watchdog
                    # trip released its stall gate: demote for the next
                    self.transport = "xla"
                    self.stats.degraded_transport = True
                return out
            # retries exhausted: the failure is a ledger signal, then
            # degrade onto the XLA transfer (scheduling state untouched)
            if probing:
                self.health.probe_result(peer, False, step=self.ticks)
            else:
                self.health.record("transport_error", peer,
                                   step=self.ticks)
            self.transport = "xla"
            self.stats.degraded_transport = True
        out = self._transport_xla(qpay, spay)
        if self._transport_pref == "dcn" and self.transport == "xla" \
                and self.stats.degraded_transport:
            # a clean degraded ship: SUSPECT clears straight back,
            # UNHEALTHY earns PROBATION (probes re-promote above)
            st = self.health.observe_clean(peer, step=self.ticks)
            if st is PeerState.HEALTHY:
                self.transport = "dcn"
                self.stats.degraded_transport = False
                self.stats.transport_repromotions += 1
        return out

    def _dcn_with_retries(self, qpay, spay):
        """The DCN wire with capped jittered backoff (the
        ``TDTPU_BOOTSTRAP_*`` pattern at ship scope): up to
        ``TDTPU_SHIP_RETRIES`` attempts (default 3), backing off
        ``TDTPU_SHIP_BACKOFF * 2**attempt`` seconds (default 0.2,
        clamped to ``TDTPU_SHIP_BACKOFF_CAP``, ledger-seeded ±50%
        jitter). Returns the landed payload or None when exhausted —
        the caller degrades. Each attempt runs under the kv_ship
        heartbeat so an armed watchdog can trip on a stalled ship."""
        import os as _os

        from triton_distributed_tpu.lang.launch import maybe_instrument

        retries = max(1, int(_os.environ.get("TDTPU_SHIP_RETRIES", "3")))
        backoff = float(_os.environ.get("TDTPU_SHIP_BACKOFF", "0.2"))
        cap = float(_os.environ.get("TDTPU_SHIP_BACKOFF_CAP", "2.0"))
        send = maybe_instrument(
            self._transport_dcn, axis=None, site="kv_ship",
            collective_id=("kv_ship", self.ticks), n=1, step=self.ticks,
        )
        for attempt in range(retries):
            try:
                return send(qpay, spay)
            except Exception:
                if attempt == retries - 1:
                    return None
                self.stats.ship_retries += 1
                delay = min(cap, backoff * (2.0 ** attempt))
                delay *= 0.5 + self.health.uniform(
                    "ship_backoff", self.ticks, attempt)
                time.sleep(delay)

    def _transport_xla(self, qpay, spay):
        """The degradation target: a plain device_put of the (already
        wire-shaped) payload onto the decode mesh."""
        from triton_distributed_tpu.tools.native import xla_kv_ship

        return xla_kv_ship(
            (qpay, spay),
            (self._q_sharding, None if spay is None else self._s_sharding),
        )

    def _transport_dcn(self, qpay, spay):
        """The quantized DCN wire: stage the payload+scale pair on the
        hybrid mesh's source role and fly both rails over the DCN axis
        with paired ``ppermute``s. (Single-process staging round-trips
        the host; on a real multislice deployment the role engines
        address one global mesh and the rails ARE the inter-slice
        bytes.)"""
        from triton_distributed_tpu.runtime.multislice import (
            kv_ship_rail,
        )
        from triton_distributed_tpu.tools.native import xla_kv_ship

        rail = kv_ship_rail(
            self.hybrid_mesh, self.dcn_axis, spay is not None
        )
        qh = np.asarray(qpay)
        stk_q = np.stack([qh, np.zeros_like(qh)])
        if spay is not None:
            sh = np.asarray(spay)
            out_q, out_s = rail(stk_q, np.stack([sh, np.zeros_like(sh)]))
            arr_q, arr_s = np.asarray(out_q)[1], np.asarray(out_s)[1]
        else:
            (out_q,) = rail(stk_q)
            arr_q, arr_s = np.asarray(out_q)[1], None
        return xla_kv_ship(
            (arr_q, arr_s),
            (self._q_sharding, None if arr_s is None else self._s_sharding),
        )

    def _commit_ships(self, force: bool = False,
                      release_source: bool = True) -> list:
        """Land ready transfers. ``force`` ignores the in-flight delay
        window and ``release_source=False`` skips freeing the prefill
        pages — the prefill-slice-death path: the payloads already left
        the dead slice, so they commit, but the source pool died with
        its slice. Returns the committed records."""
        import time as _t

        import jax
        import jax.numpy as jnp

        ready = [
            r for r in self._inflight
            if force or self.ticks - r.issued_tick >= self.ship_delay_steps
        ]
        # a launch batch shares one transported payload (same tuple
        # object on every record) and its records share issued_tick, so
        # each group lands with ONE scatter over the concatenated
        # landing pages — the commit-side mirror of the batched gather
        groups: dict = {}
        for r in ready:
            groups.setdefault(id(r.payload), []).append(r)
        for rs in groups.values():
            t0 = _t.perf_counter()
            qd, sd = rs[0].payload
            dpids = jnp.asarray(np.concatenate([
                np.asarray(r.dpids, np.int32) for r in rs
            ]))
            new_layers = self._scatter_jit(
                self.decode.state.layers, dpids, qd, sd,
            )
            jax.block_until_ready(new_layers)          # the landing fence
            self.decode.state = self.decode.state.replace(
                layers=new_layers
            )
            dt = (_t.perf_counter() - t0) * 1e3 / len(rs)
            for r in rs:
                # handoff order matters: the source frees its pinned
                # pages first, THEN the row becomes schedulable
                # (ProtocolOps.ship_commit — the transactional verb
                # servlint model-checks)
                if release_source:
                    self.decode.ops.ship_commit(
                        self.prefill, r.pslot, self.decode, r.req)
                else:
                    self.decode.commit_shipped(r.req)
                self._warm_prefix_cache(r)
                self._inflight.remove(r)
                self.stats.ships += 1
                self.stats.shipped_wire_bytes += r.wire_bytes
                self.stats.shipped_raw_bytes += r.raw_bytes
                self.stats.ship_ms.append(r.launch_ms + dt)
        return ready

    def _warm_prefix_cache(self, r: ShipRecord) -> None:
        """Decode-slice prefix-cache warm-up: the shipped pages' content
        is frozen (nothing on the decode side writes below the shipped
        cursor), so each FULL landed page registers its prefix-chain
        hash in the decode pool the moment it lands. A later request
        sharing the prefix then attaches on the decode slice without
        re-shipping — the pages are already home. Partial trailing
        pages stay private (their content is still growing)."""
        if not self.decode.pool.prefix_cache:
            return
        full = r.req.cursor // self.decode.cfg.page
        full = min(full, len(r.dpids))
        if full <= 0:
            return
        hashes = self.decode._page_hashes(r.req, full)
        for p in range(full):
            self.decode.pool.register(int(r.dpids[p]), hashes[p])

    # ------------------------------------------------------------- driving

    @property
    def idle(self) -> bool:
        return (self.prefill.idle and self.decode.idle
                and not self._ready and not self._inflight)

    def submit_trace(self, trace) -> None:
        self.prefill.submit_trace(trace)

    def tick(self) -> dict:
        """One system tick: a prefill step, ship launches/commits, a
        decode step. On hardware the two roles run concurrently on
        their own slices with the transfer in flight between them;
        the single-process harness serializes them but keeps the same
        ordering semantics (decode never observes a page before its
        commit fence). A fault-plan :class:`SliceDeath` whose step has
        arrived fails the dead role over onto the survivor first."""
        self._check_slice_deaths()
        rep_p = (None if self._dead_role == "prefill" or self.prefill.idle
                 else self.prefill.step())
        if self._dead_role is None:
            self._launch_ships()
            self._commit_ships()
        rep_d = (None if self._dead_role == "decode" or self.decode.idle
                 else self.decode.step())
        self.ticks += 1
        if (self.stats.failover_role is not None
                and self.stats.recovery_tick is None
                and all(r.done for r in self._requeued)):
            self.stats.recovery_tick = self.ticks
        return {
            "tick": self.ticks, "prefill": rep_p, "decode": rep_d,
            "inflight": len(self._inflight), "ready": len(self._ready),
        }

    # ------------------------------------------------- slice-death failover

    def _check_slice_deaths(self) -> None:
        """Consume the active plan's :class:`SliceDeath` faults: hybrid
        DCN index 0 is the prefill role, 1 the decode role (the
        ``create_hybrid_mesh`` layout bench builds)."""
        from triton_distributed_tpu.runtime import faults as _faults

        if self._dead_role is not None:
            return
        plan = _faults.active_plan()
        if plan is None:
            return
        dead = plan.dead_slices(self.ticks)
        if not dead:
            return
        roles = {0: "prefill", 1: "decode"}
        dead_roles = sorted({roles[s] for s in dead if s in roles})
        if len(dead_roles) > 1:
            raise RuntimeError(
                f"fault plan killed both serving slices by tick "
                f"{self.ticks} ({dead}) — no survivor to fail over to")
        for s in sorted(dead):
            if s not in roles:
                continue
            role = roles[s]
            self.health.record(
                "slice_death", f"slice:{s}", step=self.ticks,
                detail=f"{role} slice died at tick {self.ticks}")
            self._fail_over(role)
            return

    def _fail_over(self, dead_role: str) -> None:
        """Re-queue everything the dead slice held onto the survivor.
        Zero requests are lost and output stays token-exact: sampling is
        keyed on (seed, rid, generated-so-far), so an exact-cursor
        re-prefill (the eviction recompute discipline — prompt plus
        everything generated) resumes each stream byte-identically."""
        from dataclasses import replace as _rep

        self.stats.failover_role = dead_role
        self.stats.failover_tick = self.ticks
        requeued: list = []
        re_tokens = 0

        def requeue(req, surv):
            nonlocal re_tokens
            if req.done:
                return
            re_tokens += req.cursor
            if req.cursor > 0:
                req.evictions += 1
            req.cursor = 0
            req.slot = None
            req.parked = False
            surv.waiting.append(req)
            requeued.append(req)

        if dead_role == "decode":
            dead, surv = self.decode, self.prefill
            # the survivor becomes a FULL engine: prefill_only off,
            # completions credited to the system (decode) ledger
            surv.cfg = _rep(surv.cfg, prefill_only=False)
            surv.on_complete = self._on_failover_complete
            # requests awaiting/in a ship: their prefilled KV is intact
            # in the SURVIVOR's pool — un-park and decode in place
            kept = set()
            for req, pslot in self._ready:
                req.parked = False
                req.slot = pslot
                kept.add(id(req))
            for r in self._inflight:
                r.req.parked = False
                r.req.slot = r.pslot    # reserve_shipped repointed it
                kept.add(id(r.req))
            self._ready.clear()
            self._inflight.clear()
            # dead-pool residents lost their KV: exact-cursor re-prefill
            for req in dead.slot_req:
                if req is not None and id(req) not in kept:
                    requeue(req, surv)
        else:
            dead, surv = self.prefill, self.decode
            # payloads already transported left the dead slice — land
            # them now (their source pool is gone: no release)
            committed = self._commit_ships(force=True,
                                           release_source=False)
            handled = {id(r.req) for r in committed}
            # never-transported KV is lost: re-prefill from scratch
            for req, pslot in self._ready:
                requeue(req, surv)
                handled.add(id(req))
            self._ready.clear()
            for req in dead.slot_req:
                if req is not None and not req.done \
                        and id(req) not in handled:
                    requeue(req, surv)
        # drain the dead role's queues onto the survivor
        while dead.waiting:
            req = dead.waiting.popleft()
            surv.waiting.append(req)
            requeued.append(req)
        while dead.pending:
            surv.pending.append(dead.pending.popleft())
        # neutralize the dead engine (its device state is gone with the
        # slice; the host mirrors must read as empty so `idle` holds)
        dead.slot_req = [None] * dead.cfg.slots
        dead.table[:] = -1
        self.stats.failover_requeued = len(requeued)
        self.stats.failover_re_prefill_tokens = re_tokens
        self._requeued = requeued
        self._dead_role = dead_role

    def _on_failover_complete(self, req, slot) -> bool:
        """Post-failover completion hook on the surviving prefill-role
        engine: credit the system (decode) ledger, free the slot."""
        self.decode.stats.completed += 1
        self.decode.stats.generated_tokens += len(req.generated)
        return True

    def run(self, trace=None, max_ticks: int | None = None) -> DisaggStats:
        if trace is not None:
            self.submit_trace(trace)
        max_ticks = max_ticks or self.prefill.cfg.max_steps
        for _ in range(max_ticks):
            if self.idle:
                break
            self.tick()
        return self.stats
