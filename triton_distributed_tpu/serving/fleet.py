"""Fleet-scale serving: N engine replicas behind a health- and
cache-aware router.

The north star says millions of users; one :class:`~triton_distributed_
tpu.serving.engine.ServingEngine` (or one disaggregated pair) is the
wrong unit for that. This module is the first layer that AGGREGATES
engines: ``n`` replicas — colocated engines or
:class:`~triton_distributed_tpu.serving.engine.DisaggregatedEngine`
pairs, each on its own mesh slice carved by
:func:`~triton_distributed_tpu.runtime.topology.carve_replica_meshes` —
behind a :class:`FleetRouter` that scores admission per replica on

    score(r, req) = (1 + w_prefix · overlap_pages(r, req))
                    · health_factor(r)
                    / (1 + w_load · load_ms(r) / mean_load)

* ``overlap_pages`` — consecutive full prompt pages already RESIDENT in
  the replica's :class:`~triton_distributed_tpu.serving.state.PagePool`
  prefix registry (chain-hash lookups, the PR 7 machinery): routing a
  request where its prefix lives skips recomputing it.
* ``health_factor`` — the fleet :class:`~triton_distributed_tpu.runtime.
  health.HealthLedger` state of peer ``"replica:k"``: HEALTHY 1.0,
  SUSPECT 0.5, PROBATION probe-only, UNHEALTHY excluded — the same
  signals :func:`~triton_distributed_tpu.runtime.topology.replan_mesh`
  consumes, so the rotation grows and shrinks exactly when a replan
  would.
* ``load_ms`` — :func:`~triton_distributed_tpu.tune.perf_model.
  replica_load_ms`: the analytic step time of the replica's resident
  occupancy scaled by its queue depth, normalized by the fleet-mean
  load so the knob is scale-free (the same ``w_load`` works for
  microsecond CPU-sim steps and millisecond TPU steps). No
  measurement, so scores are reproducible.

Session affinity pins a ``req.session`` to the replica that served it
last (its KV prefix lives there); when that replica is full AND its
score (cache value vs queue depth) no longer justifies queueing, the
request SPILLS to the best-scoring replica with room and the affinity
follows the pages. Every tie-break hashes through the fleet seed (folded into
``config.interp_key`` like the fault-plan identity), so same seed ⇒
identical placement.

Robustness headline — :class:`~triton_distributed_tpu.runtime.faults.
ReplicaDeath`: when the active fault plan kills replica ``k`` at a
tick, the fleet records the fatal ``replica_death`` signal, drains
EVERYTHING the dead replica held (slots, queues, in-flight ships) back
through the router onto the survivors at cursor 0 — the recompute-
eviction discipline: re-prefilling prompt+generated resumes each
stream at its exact cursor — and, because sampling is keyed
``(seed, rid, n_generated)``, the re-placed streams are byte-identical
to the fault-free run. Zero requests are lost. A revived replica
re-enters rotation only through the PR 10 probation-probe path: clean
idle ticks earn PROBATION, seeded probes earn traffic, enough clean
probes earn HEALTHY — never a blind re-add. All replicas dead is a
loud refusal, not a hang.

Multi-tenancy (docs/SERVING.md § Multi-tenant serving): requests carry
a ``tenant`` + priority tier (interactive / batch / background, from
:class:`~triton_distributed_tpu.serving.engine.TenantConfig`), and the
fleet enforces them end to end — a deadline **slack** term in the
router score (``slack = slo_ms − modeled completion``; negative slack
outranks prefix affinity), tier-priced retry-after (a tier-r retry
waits only on the queue at rank ≤ r), engine-level **priority
preemption** through the recompute-eviction discipline, and a
:class:`BrownoutController` that sheds overload in strict
reverse-priority order (background rejected first, batch spec/chunk
budgets squeezed next, interactive last) with hysteretic recovery.
Every shed/preempt/brownout transition lands in ``stats.events`` —
the same replay-determinism contract as scale/drain/migrate.
"""

from __future__ import annotations

import time
import zlib
from collections import deque
from dataclasses import dataclass, field


def _u(*parts) -> float:
    """crc32-seeded uniform in [0, 1) — the FaultPlan/HealthLedger
    determinism idiom, reused for router tie-breaks."""
    return (zlib.crc32(repr(parts).encode()) & 0xFFFFFFFF) / 2**32


#: Kernel families a fleet replica's engines launch. ``bench.py
#: --lint`` verifies each is registered with a RESOLVABLE degradation
#: target: a replica whose engines cannot degrade cannot be safely
#: failed over onto, so the fleet inherits the engine-level
#: degradation-matrix guarantee by construction.
FLEET_ENGINE_FAMILIES = (
    "flash_decode.ragged_paged",   # every replica's serving step
    "kv_ship.pages",               # disaggregated replicas' KV wire
    "cp_decode.lse_combine",       # cp replicas' cross-rank LSE merge
)

#: Kernel families the replica→replica KV-page MIGRATION wire rides —
#: the kv_ship machinery routed fleet-internally instead of
#: prefill→decode. ``bench.py --lint`` gates that each resolves a
#: degradation target (``migration_gaps == 0``): the migration path's
#: own fallback is re-prefill at the destination, but the wire it
#: prefers must inherit the engine-level degradation guarantee or a
#: drain would wedge on the first transport fault.
MIGRATION_ENGINE_FAMILIES = (
    "kv_ship.pages",
)


# ------------------------------------------------------------- replica

@dataclass
class Replica:
    """One fleet member: an engine (colocated ``ServingEngine`` or a
    ``DisaggregatedEngine`` pair) plus its carved mesh. Duck-typed over
    both engine shapes — ``_roles`` is the flat engine list."""

    index: int
    engine: object
    mesh: object = None

    @property
    def peer(self) -> str:
        return f"replica:{self.index}"

    @property
    def _roles(self) -> tuple:
        e = self.engine
        if hasattr(e, "prefill"):          # DisaggregatedEngine
            return (e.prefill, e.decode)
        return (e,)

    @property
    def admit_role(self):
        """The engine new requests enter (the prefill half of a pair)."""
        return self._roles[0]

    def submit(self, req) -> None:
        # straight into `waiting`: the request already passed the
        # fleet-level arrival gate, the engine must not re-gate it
        self.admit_role.waiting.append(req)

    def step(self):
        e = self.engine
        return e.tick() if hasattr(e, "tick") else e.step()

    @property
    def idle(self) -> bool:
        return self.engine.idle

    def held(self) -> list:
        """Every not-done request this replica currently owns (slots,
        queues, both roles; parked/shipping requests sit in slots)."""
        out, seen = [], set()
        for role in self._roles:
            for req in (list(role.slot_req) + list(role.waiting)
                        + list(role.pending)):
                if req is not None and not req.done \
                        and id(req) not in seen:
                    seen.add(id(req))
                    out.append(req)
        return out

    def neutralize(self) -> None:
        """The replica's device state died with its slice: host mirrors
        must read empty so nothing ever schedules into it again."""
        for role in self._roles:
            role.slot_req = [None] * role.cfg.slots
            role.table[:] = -1
            role.waiting.clear()
            role.pending.clear()
        e = self.engine
        if hasattr(e, "_ready"):
            e._ready.clear()
            e._inflight.clear()

    # ------------------------------------------------- router signals

    def overlap_pages(self, req) -> int:
        """Consecutive full prompt pages resident in this replica's
        prefix registry — the cache term of the router score."""
        from triton_distributed_tpu.serving.state import page_chain_hash

        best = 0
        for role in self._roles:
            pool = role.pool
            if not pool.prefix_cache:
                continue
            page = role.cfg.page
            seq = req.seq
            h, n = 0, 0
            for p in range((len(seq) - 1) // page):
                h = page_chain_hash(h, seq[p * page:(p + 1) * page])
                if pool.lookup(h, p) is None:
                    break
                n += 1
            best = max(best, n)
        return best

    @property
    def cp(self) -> int:
        """Context-parallel factor of this replica's mesh (1 = no cp
        axis) — the long-context capability the router places by."""
        return max(
            getattr(role.model, "cp", 1) for role in self._roles)

    def fits_context(self, req) -> bool:
        """Can this replica EVER hold ``req`` end-to-end — the
        request's full KV (prompt plus every token it may generate)
        within the pool AND the per-slot table width? False means
        routing here can never admit it, whatever drains: the router's
        long-context placement filter."""
        role = self.admit_role
        tokens = len(req.seq) + int(getattr(req, "max_new", 0) or 0)
        need = max(-(-tokens // role.cfg.page), 1)
        return (need <= role.state.pages_per_seq
                and need <= role.pool.npages)

    def load_ms(self) -> float:
        """Queue-depth/step-time estimate — the perf term."""
        from triton_distributed_tpu.tune import perf_model

        return sum(perf_model.replica_load_ms(r) for r in self._roles)

    def step_model_ms(self) -> float:
        """Analytic cost of the step ABOUT to run (current occupancy)
        — the deterministic clock the fleet accumulates per replica.
        A prefilling slot bills its chunk, a decoding slot one token,
        so prefix hits (skipped prefill) show up as modeled time
        saved."""
        from triton_distributed_tpu.tune import perf_model

        return sum(perf_model.replica_step_ms(r) for r in self._roles
                   if not r.idle)

    def queue_depth(self, *, rank=None, rank_of=None) -> int:
        """Requests queued at the admission role (not yet in slots) —
        the quantity the router's ``queue_cap`` bounds. With ``rank``
        (and the fleet's ``rank_of``), only entries at rank <= rank
        count: priority admission sorts a tier-r arrival ahead of
        everything below it, so lower-tier backlog is not depth a
        tier-r client ever stands behind."""
        role = self.admit_role
        queued = list(role.waiting) + list(role.pending)
        if rank is None or rank_of is None:
            return len(queued)
        return sum(1 for q in queued if rank_of(q) <= rank)

    def can_accept(self, req) -> bool:
        """Would the admission role admit ``req`` NOW (free slot + page
        headroom)? False means routing here queues the request."""
        role = self.admit_role
        if all(r is not None for r in role.slot_req):
            return False
        first = min(role.cfg.chunk, len(req.seq))
        return (role._pages_held(first)
                <= role.pool.available - role._committed_pages())


# -------------------------------------------------------------- router

@dataclass(frozen=True)
class RouterConfig:
    """Router knobs (see docs/SERVING.md § Fleet)."""

    w_prefix: float = 1.0       # weight of the prefix-overlap term
    w_load: float = 1.0         # weight of the fleet-mean-relative load
    w_slack: float = 1.0        # weight of the deadline-deficit term
    policy: str = "scored"      # "scored" | "round_robin" (baseline)
    affinity: bool = True       # session stickiness
    # admission control: when EVERY routable replica already has this
    # many requests queued (waiting + pending on its admission role,
    # counted at the arrival's own tier — lower-tier backlog is
    # invisible to a higher-tier arrival), the fleet REJECTS the
    # arrival with a priced retry-after instead of letting `waiting`
    # grow without bound. None = unbounded (the pre-cap behavior).
    queue_cap: int | None = None


class FleetRouter:
    """Scores and places one request at a time. Stateless apart from
    the round-robin cursor and the session-affinity map; every
    tie-break is seeded, so same seed ⇒ identical placement."""

    def __init__(self, seed: int, cfg: RouterConfig | None = None):
        self.seed = seed
        self.cfg = cfg or RouterConfig()
        self._rr = 0
        self.affinity: dict = {}           # session -> replica index
        # tenant -> TenantConfig, assigned by the owning ServingFleet;
        # empty = single-tenant (no deadline term, pre-tier behavior)
        self.tenants: dict = {}

    def health_factor(self, state) -> float | None:
        """None = not routable. PROBATION returns None here — probe
        admission is the fleet's job (``ServingFleet._route_probe``),
        not a score."""
        from triton_distributed_tpu.runtime.health import PeerState

        if state is PeerState.HEALTHY:
            return 1.0
        if state is PeerState.SUSPECT:
            return 0.5
        return None                        # PROBATION / UNHEALTHY

    def slack_ms(self, replica: Replica, req) -> float | None:
        """Deadline slack of placing ``req`` at ``replica``:
        ``slo_ms − modeled completion``, where modeled completion is
        the queue already ahead (``replica.load_ms()``) plus the
        request's own remaining work (:func:`~triton_distributed_tpu.
        tune.perf_model.request_service_ms`). None when the request's
        tenant has no finite SLO — no deadline term at all."""
        import math

        tc = self.tenants.get(getattr(req, "tenant", None))
        if tc is None or not math.isfinite(tc.slo_ms):
            return None
        from triton_distributed_tpu.tune import perf_model

        return (tc.slo_ms - replica.load_ms()
                - perf_model.request_service_ms(replica.admit_role, req))

    def score(self, replica: Replica, req, state,
              mean_load: float = 0.0,
              slack: float | None = None) -> float | None:
        """The admission score. The load term enters RELATIVE to
        ``mean_load`` (the fleet mean, computed by :meth:`route`) so
        ``w_load`` is scale-free — the same knob balances microsecond
        CPU-sim steps and millisecond TPU steps. A NEGATIVE deadline
        ``slack`` divides the score by the (mean-normalized) deficit:
        the tighter a placement misses the tenant SLO, the harder it
        is penalized, so tight-deadline requests drift to the replica
        that still makes the deadline even when another holds their
        prefix."""
        hf = self.health_factor(state)
        if hf is None:
            return None
        c = self.cfg
        rel = replica.load_ms() / mean_load if mean_load > 0 else 0.0
        base = ((1.0 + c.w_prefix * replica.overlap_pages(req)) * hf
                / (1.0 + c.w_load * rel))
        if slack is not None and slack < 0:
            deficit = -slack / mean_load if mean_load > 0 else -slack
            base /= (1.0 + c.w_slack * deficit)
        return base

    def route(self, req, replicas: list, ledger) -> tuple:
        """Pick the replica for ``req`` among routable ``replicas``.
        Returns ``(replica, spilled)`` — ``spilled`` True when session
        affinity wanted a replica that is full (or gone) and the score
        said re-homing beats queueing there."""
        states = {r.index: ledger.state(r.peer) for r in replicas}
        routable = [r for r in replicas
                    if self.health_factor(states[r.index]) is not None]
        if not routable:
            raise RuntimeError(
                "fleet router: no routable replica (every replica is "
                "dead or condemned) — no survivor to fail over to")
        # long-context placement: a request whose end-to-end KV exceeds
        # a replica's pool can NEVER be admitted there — only replicas
        # whose mesh carries a cp axis wide enough stay candidates.
        # None left is a hard, priced refusal (capacity does not appear
        # by waiting), not a queue-and-hope.
        fits = [r for r in routable if r.fits_context(req)]
        if not fits:
            raise RuntimeError(
                "fleet router: no routable replica can hold this "
                "request's KV — "
                + self.long_context_refusal(req, routable))
        routable = fits
        if self.cfg.policy == "round_robin":
            r = routable[self._rr % len(routable)]
            self._rr += 1
            return r, False
        mean = sum(r.load_ms() for r in routable) / len(routable)
        slacks = {r.index: self.slack_ms(r, req) for r in routable}
        scored = [(r, self.score(r, req, states[r.index], mean,
                                 slack=slacks[r.index]))
                  for r in routable]
        # seeded tie-break: equal scores place identically under the
        # same fleet seed regardless of construction order
        scored.sort(key=lambda rs: (
            -rs[1], _u(self.seed, "tie", req.rid, rs[0].index)))
        best_with_room = next(
            ((r, s) for r, s in scored if r.can_accept(req)), None)
        sess = getattr(req, "session", None)
        spilled = False
        chosen = None
        if self.cfg.affinity and sess is not None \
                and sess in self.affinity:
            home = next((rs for rs in scored
                         if rs[0].index == self.affinity[sess]), None)
            hs = slacks.get(home[0].index) if home is not None else None
            if home is None:
                spilled = True       # home dead/condemned: re-home
            elif hs is not None and hs < 0 \
                    and not home[0].can_accept(req) \
                    and best_with_room is not None \
                    and (slacks.get(best_with_room[0].index) or 0.0) > hs:
                # deadline outranks prefix affinity: queueing at the
                # full home is MODELED to miss the tenant SLO while
                # another replica with room still makes (or misses it
                # by less) — re-home now, pages can follow the spill
                spilled = True
            elif home[0].can_accept(req) or best_with_room is None \
                    or home[1] >= best_with_room[1]:
                # queue at the home even when it is full, as long as
                # its score (resident prefix vs queue depth) still
                # beats the best replica with a free slot — waiting
                # where the pages live beats re-prefilling them
                chosen = home[0]
            else:
                spilled = True       # home full and outscored: spill
        if chosen is None:
            chosen = (best_with_room or scored[0])[0]
        if self.cfg.affinity and sess is not None:
            self.affinity[sess] = chosen.index   # affinity follows
        return chosen, spilled

    def long_context_refusal(self, req, replicas: list) -> str:
        """The priced reason no replica in ``replicas`` can hold
        ``req``: :func:`~triton_distributed_tpu.tune.perf_model.
        refuse_long_context` evaluated at the LARGEST-capacity
        candidate (the one that came closest), so the message names
        the cp factor that would have sufficed and its modeled
        per-step price."""
        from triton_distributed_tpu.tune import perf_model

        big = max(replicas, key=lambda r: min(
            r.admit_role.pool.npages,
            r.admit_role.state.pages_per_seq))
        role = big.admit_role
        tokens = len(req.seq) + int(getattr(req, "max_new", 0) or 0)
        need = max(-(-tokens // role.cfg.page), 1)
        return perf_model.refuse_long_context(
            role.model.config, role.cfg.page, need,
            pool_pages=role.pool.npages,
            pages_per_seq=role.state.pages_per_seq,
            cp=big.cp,
        ) or "long-context refusal with no over-capacity term (bug)"


# ---------------------------------------------------------- autoscaler

@dataclass(frozen=True)
class AutoscalerConfig:
    """Grow-side elasticity knobs (docs/SERVING.md § Elastic fleet).

    The pressure signal is PRICED, not counted: a tick is pressured
    when even the LIGHTEST routable replica's
    :func:`~triton_distributed_tpu.tune.perf_model.replica_load_ms`
    (the modeled wait the best possible placement pays — the projected
    p99 admission wait, since every other placement waits longer)
    exceeds ``slo_ms`` while work is actually backed up. ``window``
    consecutive pressured ticks trigger a grow; ``cooldown`` ticks must
    then pass before the next — together the flap damping that keeps a
    burst from oscillating the fleet."""

    slo_ms: float                  # projected-admission-wait SLO (model ms)
    window: int = 3                # consecutive pressured ticks to grow
    cooldown: int = 10             # min ticks between grows (flap damp)
    max_replicas: int | None = None


class FleetAutoscaler:
    """Watches the ledger-filtered routing set plus the windowed
    queue-depth/``replica_load_ms`` signal and decides WHEN the fleet
    should spawn from its reserve pool. Pure bookkeeping over
    deterministic inputs (the perf model and the tick clock), seeded
    like every fleet component — same seed and trace ⇒ identical grow
    ticks. The fleet owns HOW to grow (:meth:`ServingFleet.grow`:
    reserve mesh, probation warm-up, probe-gated admission)."""

    def __init__(self, cfg: AutoscalerConfig, seed: int = 0):
        self.cfg = cfg
        self.seed = seed
        self.pressured = 0             # consecutive pressured ticks
        self.last_grow: int | None = None
        self.history: list = []        # (tick, projected_ms, backlog)

    def pressure(self, fleet) -> bool:
        """Is THIS tick pressured? Projected wait at the lightest
        routable replica vs the SLO, gated on a real backlog."""
        routable = [
            r for r in fleet._route_candidates()
            if fleet.router.health_factor(
                fleet.health.state(r.peer)) is not None
        ]
        if not routable:
            return False
        projected = min(r.load_ms() for r in routable)
        backlog = (len(fleet.queue)
                   + sum(r.queue_depth() for r in routable))
        self.history.append((fleet.ticks, projected, backlog))
        return projected > self.cfg.slo_ms and backlog > 0

    def should_grow(self, fleet) -> bool:
        """One observation per fleet tick: update the sustained-pressure
        window, then apply the flap damps (window, cooldown,
        max_replicas)."""
        if self.pressure(fleet):
            self.pressured += 1
        else:
            self.pressured = 0
        if self.pressured < max(1, self.cfg.window):
            return False
        if self.last_grow is not None \
                and fleet.ticks - self.last_grow < self.cfg.cooldown:
            return False
        if self.cfg.max_replicas is not None \
                and len(fleet._alive()) >= self.cfg.max_replicas:
            return False
        return True


# ------------------------------------------------------------ brownout

#: Escalation ladder, strict reverse-priority order. Each level keeps
#: everything the previous one shed: ``shed_background`` bounces
#: background arrivals with a priced retry-after; ``squeeze_batch``
#: additionally throttles the batch tier's spec/chunk budgets on every
#: engine (``throttled_tiers``); ``shed_batch`` bounces batch arrivals
#: too. Interactive is NEVER shed — its protection is the whole point.
BROWNOUT_LEVELS = ("normal", "shed_background", "squeeze_batch",
                   "shed_batch")


@dataclass(frozen=True)
class BrownoutConfig:
    """Overload-controller knobs (docs/SERVING.md § Multi-tenant
    serving). Flap-damped like :class:`AutoscalerConfig`: ``window``
    consecutive pressured ticks escalate one level, ``cooldown``
    consecutive clean ticks de-escalate one level — hysteresis, so a
    border-line load doesn't oscillate the fleet between shedding and
    re-admitting every tick."""

    slo_ms: float                  # fleet-wide modeled-wait ceiling
    window: int = 3                # pressured ticks per escalation
    cooldown: int = 5              # clean ticks per de-escalation


class BrownoutController:
    """Fleet-level graceful degradation. Watches the same priced
    pressure signal as the autoscaler PLUS per-tier modeled slack (an
    arrived request whose tenant SLO is missed even at the lightest
    routable replica is pressure, whatever the absolute load), and
    sheds in strict reverse-priority order — see
    :data:`BROWNOUT_LEVELS`. Pure bookkeeping over deterministic
    inputs, seeded like every fleet component: same seed and trace ⇒
    identical shed ticks and transitions."""

    def __init__(self, cfg: BrownoutConfig, seed: int = 0):
        self.cfg = cfg
        self.seed = seed
        self.level = 0                 # index into BROWNOUT_LEVELS
        self.pressured = 0             # consecutive pressured ticks
        self.clean = 0                 # consecutive clean ticks
        self.history: list = []        # (tick, projected_ms, backlog)

    def pressure(self, fleet) -> bool:
        """Is THIS tick pressured? Backlog counts only ARRIVED fleet
        queue entries — a shed request parked at a future retry tick
        is the controller's own output, not input pressure (counting
        it would latch the brownout on forever)."""
        routable = [
            r for r in fleet._route_candidates()
            if fleet.router.health_factor(
                fleet.health.state(r.peer)) is not None
        ]
        if not routable:
            return False
        arrived = [q for q in fleet.queue if q.arrival <= fleet.ticks]
        backlog = (len(arrived)
                   + sum(r.queue_depth() for r in routable))
        projected = min(r.load_ms() for r in routable)
        self.history.append((fleet.ticks, projected, backlog))
        if backlog == 0:
            return False
        if projected > self.cfg.slo_ms:
            return True
        # per-tier slack: even a light fleet is pressured when some
        # arrived tenant's deadline is already un-meetable everywhere
        for q in arrived:
            slacks = [s for s in (fleet.router.slack_ms(r, q)
                                  for r in routable) if s is not None]
            if slacks and max(slacks) < 0:
                return True
        return False

    def observe(self, fleet) -> None:
        """One observation per fleet tick: escalate after ``window``
        pressured ticks, de-escalate after ``cooldown`` clean ticks,
        log every transition into the replay-determinism event
        stream."""
        if self.pressure(fleet):
            self.pressured += 1
            self.clean = 0
            if self.pressured >= max(1, self.cfg.window) \
                    and self.level < len(BROWNOUT_LEVELS) - 1:
                old = BROWNOUT_LEVELS[self.level]
                self.level += 1
                self.pressured = 0
                fleet._log_event(
                    "brownout", -1,
                    f"{old}->{BROWNOUT_LEVELS[self.level]}")
        else:
            self.clean += 1
            self.pressured = 0
            if self.clean >= max(1, self.cfg.cooldown) \
                    and self.level > 0:
                old = BROWNOUT_LEVELS[self.level]
                self.level -= 1
                self.clean = 0
                fleet._log_event(
                    "brownout", -1,
                    f"{old}->{BROWNOUT_LEVELS[self.level]}")

    def sheds(self, rank: int) -> bool:
        """Does the CURRENT level shed an arrival of this tier rank?
        Strict reverse priority: background (rank 2) from
        ``shed_background`` up, batch (rank 1) only at ``shed_batch``,
        interactive (rank 0) never."""
        if rank >= 2:
            return self.level >= 1
        if rank == 1:
            return self.level >= 3
        return False

    @property
    def squeezed(self) -> frozenset:
        """Tiers whose spec/chunk budgets every engine throttles at
        the current level (``ServingEngine.throttled_tiers``)."""
        return (frozenset({"batch"}) if self.level >= 2
                else frozenset())


# --------------------------------------------------------------- stats

@dataclass
class FleetStats:
    """Fleet-level accounting. Per-request ticks (TTFT/TPOT) use the
    deterministic tick clock; wall-time aggregates use the per-replica
    step time the fleet accumulates (replicas run concurrently on
    their own slices in production, so fleet wall = slowest replica)."""

    submitted: int = 0
    routed: dict = field(default_factory=dict)     # replica -> count
    affinity_hits: int = 0
    spills: int = 0
    probes: int = 0
    # admission control (RouterConfig.queue_cap): arrivals rejected
    # because every routable replica's queue was at cap, and the priced
    # retry-after each rejection was told to wait (perf-model ms)
    admission_rejections: int = 0
    retry_after_ms: list = field(default_factory=list)
    deaths: list = field(default_factory=list)     # (replica, tick)
    failover_requeued: int = 0
    failover_re_prefill_tokens: int = 0
    replica_time: dict = field(default_factory=dict)  # replica -> s
    # modeled (perf-model) step time per replica, ms — deterministic,
    # and sensitive to compute actually saved (prefix hits skip
    # prefill chunks), unlike host wall time on the CPU harness
    replica_model_ms: dict = field(default_factory=dict)
    # folded stats of engines that died/were replaced (revive swaps
    # the engine object; its counters must not vanish)
    retired_prefix_hits: int = 0
    retired_evictions: int = 0
    retired_generated: int = 0
    retired_preemptions: int = 0
    retired_tenant_preemptions: dict = field(default_factory=dict)
    # --- multi-tenant brownout / maintenance ---
    sheds: dict = field(default_factory=dict)         # tier -> count
    tenant_sheds: dict = field(default_factory=dict)  # tenant -> count
    retunes: list = field(default_factory=list)  # (tick, replica, n)
    records: dict = field(default_factory=dict)
    # rid -> {arrival, first_token_tick, completion_tick, n, tokens}
    # --- elastic fleet (grow / drain / migrate) ---
    # the replay-determinism object: every scale/drain/migration event
    # as (kind, replica, tick, detail) in occurrence order — same fleet
    # seed and trace ⇒ byte-identical list (test-pinned)
    events: list = field(default_factory=list)
    grows: list = field(default_factory=list)      # (replica, tick)
    drains: list = field(default_factory=list)     # (replica, start, done)
    drain_requeued: int = 0        # queued work handed back by a drain
    migrations: int = 0
    migrated_pages: int = 0
    migration_wire_bytes: int = 0
    # (migrate_ms, reprefill_ms) per migration — the perf_model.
    # migrate_vs_reprefill_ms verdict that justified each wire trip
    migration_priced: list = field(default_factory=list)
    migration_refusals: int = 0    # priced: re-prefill beat the wire
    migration_failures: int = 0    # wire exhausted; re-prefill fallback
    # --- long-context placement ---
    # (rid, priced reason) per arrival whose end-to-end KV fits NO
    # routable replica — refused outright (perf_model.
    # refuse_long_context prices the cp factor that would have held it)
    long_context_refusals: list = field(default_factory=list)

    @property
    def migrations_cheaper(self) -> int:
        """Migrations whose shipped wire priced UNDER the modeled
        re-prefill — by construction all of them (the fleet refuses the
        rest), so this equals ``migrations`` unless the pricing gate is
        broken; the CI smoke asserts it is nonzero."""
        return sum(1 for w, r in self.migration_priced if w < r)

    @property
    def completed(self) -> int:
        return sum(1 for r in self.records.values()
                   if r["completion_tick"] is not None)

    @property
    def lost_requests(self) -> int:
        return self.submitted - self.completed

    def _recs(self, tenant: str | None = None) -> list:
        if tenant is None:
            return list(self.records.values())
        return [r for r in self.records.values()
                if getattr(r["req"], "tenant", "default") == tenant]

    def _ttfts(self, tenant: str | None = None) -> list:
        return [r["first_token_tick"] - r["arrival"]
                for r in self._recs(tenant)
                if r["first_token_tick"] is not None]

    def _tpots(self, tenant: str | None = None) -> list:
        return [(r["completion_tick"] - r["first_token_tick"])
                / max(r["n"] - 1, 1)
                for r in self._recs(tenant)
                if r["completion_tick"] is not None]

    @property
    def p99_ttft_ticks(self) -> float:
        import numpy as np

        ts = self._ttfts()
        return float(np.percentile(np.asarray(ts), 99)) if ts else 0.0

    @property
    def p99_tpot_ticks(self) -> float:
        import numpy as np

        ts = self._tpots()
        return float(np.percentile(np.asarray(ts), 99)) if ts else 0.0

    def per_tenant(self, preemptions: dict | None = None) -> dict:
        """tenant -> goodput/latency/robustness view: submitted,
        completed, generated tokens, p99 TTFT/TPOT in fleet ticks,
        sheds, and (when the fleet passes its merged map) preemptions
        — the per-tenant observability surface the multi-tenant bench
        and CI smoke assert on."""
        import numpy as np

        out: dict = {}
        for rec in self.records.values():
            t = getattr(rec["req"], "tenant", "default")
            d = out.setdefault(t, {
                "submitted": 0, "completed": 0, "generated": 0,
                "p99_ttft_ticks": 0.0, "p99_tpot_ticks": 0.0,
                "sheds": 0, "preemptions": 0,
            })
            d["submitted"] += 1
            if rec["completion_tick"] is not None:
                d["completed"] += 1
                d["generated"] += rec["n"]
        for t, d in out.items():
            ts = self._ttfts(t)
            if ts:
                d["p99_ttft_ticks"] = float(
                    np.percentile(np.asarray(ts), 99))
            tp = self._tpots(t)
            if tp:
                d["p99_tpot_ticks"] = float(
                    np.percentile(np.asarray(tp), 99))
            d["sheds"] = self.tenant_sheds.get(t, 0)
            d["preemptions"] = (preemptions or {}).get(t, 0)
        return out


# --------------------------------------------------------------- fleet

class ServingFleet:
    """N replicas + a router + a fleet health ledger, driven on one
    deterministic tick clock. See the module docstring for the scoring
    and failover contracts.

    ``engines`` — list of built engines (one per replica; pair them
    with meshes from ``carve_replica_meshes`` on real topologies).
    ``seed`` — the fleet routing seed; installed via
    ``config.set_fleet_seed`` for the duration of :meth:`run` so cached
    kernel builds can't leak across differently-routed fleets.
    ``reserve`` — spare capacity the autoscaler may spawn from: a list
    of engines, zero-arg engine factories, or ``(factory, mesh)`` pairs
    (meshes from ``carve_replica_meshes(..., reserve=n)``). Factories
    defer building until the grow actually happens.
    ``autoscaler`` — an :class:`AutoscalerConfig`; None disables
    ledger-driven grow (the pre-elastic behavior).
    ``perf_spec`` — optional TpuSpec override for the migration pricing
    (tests flip the migrate-vs-reprefill verdict by shrinking
    ``dcn_gbps``).
    ``tenants`` — ``{tenant: TenantConfig}``; enables the deadline
    slack term, tier-priced retry-after, per-tenant fair share, and
    priority preemption (the map is pushed into every engine).
    ``brownout`` — a :class:`BrownoutConfig`; None disables
    load-shedding (the pre-brownout behavior).
    ``retune_every`` — run the grid-schedule ``background_retune`` in
    the fleet's own maintenance window every N ticks (low-pressure
    ticks only; suppressed during brownout). None disables.
    """

    def __init__(self, engines, *, seed: int = 0,
                 router: RouterConfig | None = None, health=None,
                 meshes=None, reserve=None, autoscaler=None,
                 perf_spec=None, tenants=None, brownout=None,
                 retune_every: int | None = None, ops=None):
        from triton_distributed_tpu.runtime.health import HealthLedger
        from triton_distributed_tpu.serving.protocol import ProtocolOps

        if not engines:
            raise ValueError("a fleet needs at least one replica")
        if router is not None and router.queue_cap is not None \
                and router.queue_cap < 1:
            raise ValueError(
                f"queue_cap must be >= 1 (got {router.queue_cap}) — "
                "a zero cap rejects every arrival forever")
        meshes = meshes or [None] * len(engines)
        self.replicas = [Replica(i, e, m)
                         for i, (e, m) in enumerate(zip(engines, meshes))]
        self.seed = seed
        # fleet-level protocol verbs live behind the same seam the
        # engines use, so servlint can drive (or mutate) them too
        self.ops = ops if ops is not None else ProtocolOps()
        self.health = health if health is not None else HealthLedger(
            seed=seed)
        self.router = FleetRouter(seed, router)
        self.queue: deque = deque()        # fleet arrivals, by time
        self.ticks = 0
        self.stats = FleetStats()
        self._dead: set = set()            # currently-dead replica idx
        self._death_handled: set = set()   # faults already consumed
        self._probing: dict = {}           # replica idx -> probe tick
        self._draining: dict = {}          # replica idx -> drain start
        self._retired: set = set()         # cleanly drained, gone
        self._reserve = list(reserve or [])
        self.autoscaler = (FleetAutoscaler(autoscaler, seed=seed)
                           if autoscaler is not None else None)
        self.perf_spec = perf_spec
        self.tenants = dict(tenants or {})
        self.router.tenants = self.tenants
        self.brownout = (BrownoutController(brownout, seed=seed)
                         if brownout is not None else None)
        self.retune_every = retune_every
        for r in self.replicas:
            self._wire_tenancy(r)

    def _wire_tenancy(self, replica: Replica) -> None:
        """Push the fleet tenant map into the replica's engines and
        hook engine preemptions into the replay-determinism event
        stream — called for every replica that enters the fleet
        (construction, grow, revive)."""
        for role in replica._roles:
            if self.tenants:
                role.tenants = self.tenants

            def on_preempt(by, victim, _idx=replica.index, _role=role):
                from triton_distributed_tpu.serving.engine import TIERS

                self._log_event(
                    "preempt", _idx,
                    f"rid={victim.rid} tier="
                    f"{TIERS[_role._rank(victim)]} by={by.rid}")

            role.on_preempt = on_preempt

    def _rank_of(self, req) -> int:
        """Fleet-side tier rank of a request — per-request priority
        first, then its tenant's tier, interactive (0) by default."""
        from triton_distributed_tpu.serving.engine import (
            DEFAULT_TENANT, tier_rank,
        )

        tc = self.tenants.get(getattr(req, "tenant", "default"),
                              DEFAULT_TENANT)
        return tier_rank(getattr(req, "priority", None) or tc.priority)

    # ---------------------------------------------------------- intake

    def submit(self, req) -> None:
        self.queue.append(req)
        self.stats.submitted += 1
        self.stats.records[req.rid] = {
            "arrival": req.arrival, "first_token_tick": None,
            "completion_tick": None, "n": 0, "tokens": None,
            "req": req,
        }

    def submit_trace(self, trace) -> None:
        for r in sorted(trace, key=lambda r: r.arrival):
            self.submit(r)

    @property
    def idle(self) -> bool:
        return (not self.queue
                and not self._draining
                and all(r.idle for r in self._alive()))

    def _alive(self) -> list:
        return [r for r in self.replicas
                if r.index not in self._dead
                and r.index not in self._retired]

    def _route_candidates(self) -> list:
        """Replicas the router may place NEW work on: alive and not
        draining — a draining replica finishes (or migrates) what it
        holds and admits nothing."""
        return [r for r in self._alive()
                if r.index not in self._draining]

    def rotation(self) -> tuple:
        """Replica indices currently receiving scored traffic — the
        ledger-driven grow/shrink surface (PROBATION members rejoin
        probe-first; UNHEALTHY members are out; draining members have
        stopped admitting)."""
        from triton_distributed_tpu.runtime.health import PeerState

        out = []
        for r in self._route_candidates():
            st = self.health.state(r.peer)
            if st not in (PeerState.UNHEALTHY, PeerState.PROBATION):
                out.append(r.index)
        return tuple(out)

    # -------------------------------------------------------- dispatch

    def _dispatch(self) -> int:
        """Route every arrived request. Runs under the
        ``router_dispatch`` chaos site: a fault-plan Stall there wedges
        the WHOLE fleet's admission (every replica starves at once) and
        an armed watchdog names it."""
        from triton_distributed_tpu.lang.launch import maybe_instrument

        body = maybe_instrument(
            self._dispatch_body, axis=None, site="router_dispatch",
            collective_id=("router_dispatch", self.ticks), n=1,
            step=self.ticks,
        )
        return body()

    def _dispatch_body(self) -> int:
        n = 0
        while self.queue and self.queue[0].arrival <= self.ticks:
            req = self.queue.popleft()
            if self._refuse_long_context(req):
                continue
            if self._shed_brownout(req):
                continue
            if self._reject_overload(req):
                continue
            target = self._route_probe(req)
            spilled = False
            if target is None:
                sess = getattr(req, "session", None)
                home_idx = (self.router.affinity.get(sess)
                            if sess is not None else None)
                target, spilled = self.router.route(
                    req, self._route_candidates(), self.health)
                if spilled and home_idx is not None \
                        and home_idx != target.index:
                    # the session re-homed but its prefix pages still
                    # live at the old home: ship them instead of
                    # letting admission re-prefill (when priced)
                    self._migrate_prefix(req, home_idx, target)
            target.submit(req)
            self.stats.routed[target.index] = (
                self.stats.routed.get(target.index, 0) + 1)
            if spilled:
                self.stats.spills += 1
            elif getattr(req, "session", None) is not None:
                self.stats.affinity_hits += 1
            n += 1
        return n

    def _refuse_long_context(self, req) -> bool:
        """Long-context placement gate: an arrival whose end-to-end KV
        fits NO routable replica is refused OUTRIGHT with the priced
        reason (``stats.long_context_refusals``). Unlike an overload
        bounce there is no retry-after — waiting cannot make pool
        capacity appear, so a priced retry would be a promise the
        fleet can never honor. The request is marked done with its
        ``refusal`` reason attached (the loud failure the client
        sees), and the event log records it for replay pins."""
        routable = self._routable()
        if not routable:
            return False       # route() raises the every-replica-dead error
        if any(r.fits_context(req) for r in routable):
            return False
        reason = self.router.long_context_refusal(req, routable)
        self.stats.long_context_refusals.append((req.rid, reason))
        self._log_event("long_context_refusal", -1, f"rid={req.rid}")
        req.refusal = reason
        req.done = True
        return True

    def _reject_overload(self, req) -> bool:
        """Admission control (``RouterConfig.queue_cap``): when every
        routable replica's queue is at cap, the arrival is REJECTED
        with a priced retry-after instead of deepening some replica's
        ``waiting`` without bound. The retry-after is the perf model's
        estimate of when the LIGHTEST ROUTABLE queue will have drained
        at the request's own tier (:meth:`_priced_retry`) — so a
        client backs off proportionally to the congestion its tier
        actually sees, not by a blind constant. The rejected request
        re-enters the fleet queue at the retry tick (the harness's
        stand-in for the client honoring Retry-After), so a flooded
        trace finishes with zero LOST requests — later, not never."""
        cap = self.router.cfg.queue_cap
        if cap is None:
            return False
        routable = self._routable()
        if not routable:
            return False       # route() raises the every-replica-dead error
        # depth at the arrival's OWN tier: a batch flood queued below
        # an interactive arrival is not depth it stands behind (the
        # same tier-visibility the priced retry uses) — single-tenant
        # fleets see the full queue, the pre-tier cap exactly
        rank = self._rank_of(req)
        if min(r.queue_depth(rank=rank, rank_of=self._rank_of)
               for r in routable) < cap:
            return False
        retry_ms, retry_ticks = self._priced_retry(req, routable)
        self.stats.admission_rejections += 1
        self._requeue_priced(req, retry_ms, retry_ticks)
        return True

    def _routable(self) -> list:
        """Route candidates the ledger actually admits traffic to —
        PROBATION and UNHEALTHY excluded. Every retry-after price MUST
        come off this set: a PROBATION replica's empty queue is not a
        wait any client can actually buy (it only takes seeded
        probes), so pricing off it would hand out retry-afters the
        fleet cannot honor (pinned by test)."""
        return [
            r for r in self._route_candidates()
            if self.router.health_factor(self.health.state(r.peer))
            is not None
        ]

    def _priced_retry(self, req, routable) -> tuple:
        """``(retry_ms, retry_ticks)`` for a bounced arrival: the
        modeled drain of the lightest ROUTABLE replica's queue AT THE
        REQUEST'S OWN TIER. Priority admission sorts tier-r retries
        ahead of every lower tier, so a tier-r client waits only on
        the queued work at rank ≤ r — per-tenant retry-after prices by
        the tenant's own tier, not the fleet mean. Single-tenant
        fleets price identically to the pre-tier behavior (every
        request is rank 0, the filter passes the whole queue)."""
        import math

        from triton_distributed_tpu.tune import perf_model

        light = min(routable, key=lambda r: (r.queue_depth(),
                                             r.load_ms(), r.index))
        rank = self._rank_of(req)
        role = light.admit_role
        ahead = sum(1 for q in list(role.waiting) + list(role.pending)
                    if self._rank_of(q) <= rank)
        retry_ms = perf_model.tiered_replica_load_ms(role, ahead)
        for other in light._roles:
            if other is not role:
                retry_ms += perf_model.replica_load_ms(other)
        step_ms = light.step_model_ms()
        retry_ticks = (max(1, math.ceil(retry_ms / step_ms))
                       if step_ms > 0 else 1)
        return retry_ms, retry_ticks

    def _requeue_priced(self, req, retry_ms: float,
                        retry_ticks: int) -> None:
        req.arrival = self.ticks + retry_ticks
        req.admission_retries = getattr(req, "admission_retries", 0) + 1
        self.stats.retry_after_ms.append(retry_ms)
        # re-enter in arrival order (stable sort keeps FIFO among ties)
        self.queue.append(req)
        self.queue = deque(sorted(self.queue, key=lambda r: r.arrival))

    def _shed_brownout(self, req) -> bool:
        """Brownout load-shedding: while the overload controller sits
        at a level that sheds this arrival's tier, bounce it with the
        same tier-priced retry-after as admission control — strict
        reverse-priority order (background first, batch only at the
        deepest level, interactive never) and zero lost requests (the
        retry re-enters the fleet queue and lands once the controller
        recovers)."""
        if self.brownout is None or self.brownout.level == 0:
            return False
        rank = self._rank_of(req)
        if not self.brownout.sheds(rank):
            return False
        routable = self._routable()
        if not routable:
            return False
        from triton_distributed_tpu.serving.engine import TIERS

        retry_ms, retry_ticks = self._priced_retry(req, routable)
        tier = TIERS[min(rank, len(TIERS) - 1)]
        self.stats.sheds[tier] = self.stats.sheds.get(tier, 0) + 1
        t = getattr(req, "tenant", "default")
        self.stats.tenant_sheds[t] = (
            self.stats.tenant_sheds.get(t, 0) + 1)
        self._log_event(
            "shed", -1,
            f"rid={req.rid} tier={tier} "
            f"level={BROWNOUT_LEVELS[self.brownout.level]} "
            f"retry@{self.ticks + retry_ticks}")
        self._requeue_priced(req, retry_ms, retry_ticks)
        return True

    def _route_probe(self, req):
        """A PROBATION replica whose seeded probe is due gets this
        request as its probe — traffic is the probe, exactly like the
        engine-level kernel probes."""
        from triton_distributed_tpu.runtime.health import PeerState

        for r in self._route_candidates():
            if r.index in self._probing:
                continue
            if self.health.state(r.peer) is PeerState.PROBATION \
                    and self.health.probe_due(r.peer, self.ticks):
                self._probing[r.index] = self.ticks
                self.stats.probes += 1
                return r
        return None

    # ------------------------------------------------------------ tick

    def tick(self) -> dict:
        """One fleet tick: consume replica deaths, maybe grow, route
        arrivals, advance drains (migrate-or-finish), step every live
        replica (concurrent slices in production; the host harness
        serializes them on one clock)."""
        from triton_distributed_tpu.runtime.health import PeerState

        self._check_replica_deaths()
        self._maybe_grow()
        self._observe_brownout()
        routed = self._dispatch()
        self._advance_drains()
        stepped = 0
        for r in self._alive():
            st = self.health.state(r.peer)
            if st is PeerState.UNHEALTHY:
                # a revived replica idles cleanly until the ledger
                # grants PROBATION — the gate before any probe traffic
                self.health.observe_clean(r.peer, step=self.ticks)
                continue
            if r.idle:
                continue
            self.stats.replica_model_ms[r.index] = (
                self.stats.replica_model_ms.get(r.index, 0.0)
                + r.step_model_ms())
            t0 = time.perf_counter()
            try:
                r.step()
            except Exception:
                if r.index in self._probing:
                    del self._probing[r.index]
                    self.health.probe_result(r.peer, False,
                                             step=self.ticks)
                    continue
                raise
            self.stats.replica_time[r.index] = (
                self.stats.replica_time.get(r.index, 0.0)
                + time.perf_counter() - t0)
            stepped += 1
            if r.index in self._probing:
                del self._probing[r.index]
                self.health.probe_result(r.peer, True, step=self.ticks)
        self._maybe_retune()
        self._update_records()
        self.ticks += 1
        return {"tick": self.ticks, "routed": routed,
                "stepped": stepped, "queued": len(self.queue)}

    def _observe_brownout(self) -> None:
        """One brownout observation per tick, then project the current
        squeeze set onto every live engine — ``throttled_tiers`` is
        what ``_chunk_for`` and the speculative ``_plan_row`` read to
        halve the batch tier's chunk and cap its draft budget."""
        if self.brownout is None:
            return
        self.brownout.observe(self)
        squeezed = self.brownout.squeezed
        for r in self._alive():
            for role in r._roles:
                role.throttled_tiers = squeezed

    def _maybe_retune(self) -> None:
        """Grid-schedule retuning inside the fleet's own MAINTENANCE
        WINDOW (PR-15 follow-on): every ``retune_every`` ticks, IF the
        tick is low-pressure — no arrived backlog, every routable
        queue empty, brownout at normal (an overloaded fleet has no
        business burning host time on schedule search). Retunes the
        hottest shape ledger among the routable replicas via
        ``background_retune`` (dryrun: perf-model priced, store
        persisted) and joins the thread inside the window — the next
        engine build resolves the winners for free."""
        if not self.retune_every or self.ticks == 0 \
                or self.ticks % self.retune_every:
            return
        if self.brownout is not None and self.brownout.level > 0:
            return
        if any(q.arrival <= self.ticks for q in self.queue):
            return
        routable = self._routable()
        if not routable or any(r.queue_depth() > 0 for r in routable):
            return

        def heat(replica):
            return sum(float(ent[1]) for role in replica._roles
                       for ent in role.stats.shape_ledger.values())

        target = max(routable, key=lambda r: (
            heat(r), -_u(self.seed, "retune", self.ticks, r.index)))
        role = target.admit_role
        if not role.stats.shape_ledger:
            return
        from triton_distributed_tpu.tune.traffic import (
            background_retune,
        )

        mc = role.model.config
        t = background_retune(
            role.stats, mesh_shape=(role.model.tp,),
            wire="int8" if getattr(mc, "kv_quant", None) is not None
            else None,
            dryrun=True)
        t.join()
        self.stats.retunes.append(
            (self.ticks, target.index, len(t.reports)))
        self._log_event("retune", target.index,
                        f"reports={len(t.reports)}")

    def _update_records(self) -> None:
        # the Request objects are shared with the engines (engines
        # mutate them in place), so the fleet reads progress directly
        for rec in self.stats.records.values():
            req = rec["req"]
            if req.generated and rec["first_token_tick"] is None:
                rec["first_token_tick"] = self.ticks
            if req.done and rec["completion_tick"] is None:
                rec["completion_tick"] = self.ticks
                rec["n"] = len(req.generated)
                rec["tokens"] = list(req.generated)

    def run(self, trace=None, max_ticks: int = 10_000) -> FleetStats:
        from triton_distributed_tpu import config as _config

        if trace is not None:
            self.submit_trace(trace)
        prev = _config.fleet_seed()
        _config.set_fleet_seed(self.seed)
        try:
            for _ in range(max_ticks):
                if self.idle:
                    break
                self.tick()
        finally:
            _config.set_fleet_seed(prev)
        return self.stats

    # -------------------------------------------------------- failover

    def _check_replica_deaths(self) -> None:
        """Consume the active plan's :class:`ReplicaDeath` faults —
        the fleet twin of ``DisaggregatedEngine._check_slice_deaths``."""
        from triton_distributed_tpu.runtime import faults as _faults

        plan = _faults.active_plan()
        if plan is None:
            return
        for k in plan.dead_replicas(self.ticks):
            if k in self._death_handled or k >= len(self.replicas):
                continue
            self._death_handled.add(k)
            self._kill(k)

    def _kill(self, k: int) -> None:
        self._dead.add(k)
        # a death interrupts any in-progress drain of the same replica:
        # the remaining resident rows take the failover path below
        # (cursor-0 requeue) instead of migrating — still zero lost
        interrupted = self._draining.pop(k, None)
        if not self._alive():
            raise RuntimeError(
                f"fault plan killed every fleet replica by tick "
                f"{self.ticks} — no survivor to fail over to")
        replica = self.replicas[k]
        self.health.record(
            "replica_death", replica.peer, step=self.ticks,
            detail=f"replica {k} died at tick {self.ticks}")
        self.stats.deaths.append((k, self.ticks))
        self._log_event(
            "death", k,
            f"mid-drain (started@{interrupted})"
            if interrupted is not None else "")
        self._retire_engine(replica)
        # drain: everything the replica held re-enters the FLEET queue
        # at cursor 0 (the recompute-eviction discipline: re-prefilling
        # prompt+generated resumes the exact cursor) and re-routes onto
        # the survivors this same tick — zero lost requests, and the
        # request-keyed sampler keeps the streams byte-identical
        drained = self.ops.failover_requeue(
            replica.held(), self.queue, self.stats)
        self.stats.failover_requeued += len(drained)
        replica.neutralize()
        # the dead replica's sessions must re-home on their next request
        for sess, idx in list(self.router.affinity.items()):
            if idx == k:
                del self.router.affinity[sess]
        # SV007 (servlint counterexample): if this death left ONLY
        # draining survivors, the fleet is permanently unroutable — the
        # backlog (including the rows just requeued above) waits on
        # replicas that admit no routed work, and drain completion
        # itself can wedge when the drain's migration target was the
        # replica that just died. Cancel the surviving drains: capacity
        # loss outranks the drain intent.
        if not self._route_candidates():
            for j in sorted(self._draining):
                self._draining.pop(j)
                self._log_event("drain_cancel", j, f"death@{k}")

    def _retire_engine(self, replica: Replica) -> None:
        for role in replica._roles:
            self.stats.retired_prefix_hits += role.stats.prefix_hits
            self.stats.retired_evictions += role.stats.evictions
            self.stats.retired_generated += role.stats.generated_tokens
            self.stats.retired_preemptions += role.stats.preemptions
            for t, n in role.stats.tenant_preemptions.items():
                self.stats.retired_tenant_preemptions[t] = (
                    self.stats.retired_tenant_preemptions.get(t, 0) + n)

    def revive(self, k: int, engine=None) -> None:
        """Bring replica ``k`` back with a FRESH engine (its old device
        state died with it). The ledger still holds the fatal
        ``replica_death`` record, so the replica re-enters rotation
        only through probation probes — never a blind re-add."""
        if k not in self._dead:
            raise ValueError(f"replica {k} is not dead")
        if engine is not None:
            self.replicas[k].engine = engine
        self._wire_tenancy(self.replicas[k])
        self._dead.discard(k)

    # ---------------------------------------------------------- elastic

    def _log_event(self, kind: str, replica: int,
                   detail: str = "") -> None:
        self.stats.events.append((kind, replica, self.ticks, detail))

    def _maybe_grow(self) -> None:
        if self.autoscaler is None or not self._reserve:
            return
        if self.autoscaler.should_grow(self):
            self.grow()

    def grow(self) -> int:
        """Spawn one replica from the reserve pool. The newcomer enters
        through the ledger, never blindly: the spawn is recorded as a
        fatal signal (UNHEALTHY), clean idle ticks earn PROBATION, and
        the router hands it traffic only as seeded probes until
        ``promote_after`` clean probes promote it to HEALTHY — the same
        PR 10 path a revived replica walks. Returns the new index."""
        if not self._reserve:
            raise ValueError("grow: the reserve pool is empty")
        spare = self._reserve.pop(0)
        mesh = None
        if isinstance(spare, tuple):
            spare, mesh = spare
        engine = spare() if callable(spare) else spare
        idx = len(self.replicas)
        replica = Replica(idx, engine, mesh)
        self.replicas.append(replica)
        self._wire_tenancy(replica)
        self.health.record(
            "autoscale_spawn", replica.peer, step=self.ticks,
            detail=f"replica {idx} spawned from the reserve pool",
            fatal=True)
        if self.autoscaler is not None:
            self.autoscaler.last_grow = self.ticks
            self.autoscaler.pressured = 0
        self.stats.grows.append((idx, self.ticks))
        self._log_event("grow", idx, "spawned from reserve")
        return idx

    def drain(self, k: int) -> None:
        """Planned retirement — the dual of :meth:`_kill`. Replica
        ``k`` stops admitting immediately (out of the routing set and
        the rotation); its queued-but-not-resident work re-enters the
        fleet queue now; resident rows either finish in place or
        MIGRATE their committed KV pages to a surviving replica (when
        :func:`~triton_distributed_tpu.tune.perf_model.
        migrate_vs_reprefill_ms` prices the wire under the recompute
        and a destination can reserve landing pages); once empty the
        replica retires cleanly. A chaos ``ReplicaDeath`` mid-drain
        falls back to the failover path — zero requests lost either
        way."""
        if k in self._dead or k in self._retired \
                or k >= len(self.replicas):
            raise ValueError(f"replica {k} is dead/retired/unknown")
        if k in self._draining:
            return
        others = [r for r in self._route_candidates()
                  if r.index != k and self.router.health_factor(
                      self.health.state(r.peer)) is not None]
        if not others:
            raise RuntimeError(
                f"cannot drain replica {k}: it is the last routable "
                "replica — grow or revive first")
        self._draining[k] = self.ticks
        replica = self.replicas[k]
        requeued = 0
        for role in replica._roles:
            requeued += len(self.ops.drain_requeue(role, self.queue))
        if requeued:
            self.queue = deque(sorted(self.queue,
                                      key=lambda r: r.arrival))
            self.stats.drain_requeued += requeued
        # session affinities stay pointed here until their next request
        # re-routes — the spill-migration path needs the old home
        self._log_event("drain_start", k, f"requeued={requeued}")

    def _advance_drains(self) -> None:
        """One drain step per draining replica: try to migrate every
        resident row off it (parked rows ride their own ship machinery
        and finish first), retire when nothing is left."""
        for k in sorted(self._draining):
            replica = self.replicas[k]
            for role in replica._roles:
                for req in list(role.slot_req):
                    if req is None or req.done or req.parked:
                        continue
                    self._try_migrate_live(req, replica, role)
            if not replica.held() and replica.idle:
                self._retire(k)

    def _retire(self, k: int) -> None:
        replica = self.replicas[k]
        start = self._draining.pop(k)
        self._retired.add(k)
        self._retire_engine(replica)
        replica.neutralize()
        for sess, idx in list(self.router.affinity.items()):
            if idx == k:
                del self.router.affinity[sess]
        self.stats.drains.append((k, start, self.ticks))
        self._log_event("drain_done", k, f"started@{start}")

    # ------------------------------------------------------- migration

    def _price_migration(self, role, n_pages: int) -> tuple:
        from triton_distributed_tpu.tune import perf_model

        mc = role.model.config
        hkv = mc.n_kv_heads
        return perf_model.migrate_vs_reprefill_ms(
            n_pages, page=role.cfg.page, hkv=hkv,
            g=mc.n_heads // max(hkv, 1), d=mc.head_dim,
            hidden=mc.hidden, n_layers=mc.n_layers,
            chunk=role.cfg.chunk,
            quant=getattr(mc, "kv_quant", None) is not None,
            spec=self.perf_spec)

    def _landing_shardings(self, role, with_scale: bool) -> tuple:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        # payload (L·2, P, Hkv, page[, D]): KV heads stay sharded over
        # the destination's tp axis, like the pools they land in — the
        # DisaggregatedEngine wire discipline
        q = NamedSharding(role.model.mesh, P(None, None, role.model.tp_axis))
        return q, (q if with_scale else None)

    def _migrate_transport(self, payload, dst_role):
        """The replica→replica wire: the kv_ship XLA transfer onto the
        destination mesh under the ``kv_migrate`` chaos site, with the
        PR 10 capped-jittered retry/backoff. Returns the landed payload
        or None when exhausted — the caller rolls back and the row
        falls back to re-prefill (this path's degradation target)."""
        import os as _os

        from triton_distributed_tpu.lang.launch import maybe_instrument
        from triton_distributed_tpu.tools.native import xla_kv_ship

        qpay, spay = payload
        shard = self._landing_shardings(dst_role, spay is not None)
        send = maybe_instrument(
            lambda: xla_kv_ship((qpay, spay), shard), axis=None,
            site="kv_migrate",
            collective_id=("kv_migrate", self.ticks), n=1,
            step=self.ticks)
        retries = max(1, int(_os.environ.get("TDTPU_SHIP_RETRIES", "3")))
        backoff = float(_os.environ.get("TDTPU_SHIP_BACKOFF", "0.2"))
        cap = float(_os.environ.get("TDTPU_SHIP_BACKOFF_CAP", "2.0"))
        for attempt in range(retries):
            try:
                return send()
            except Exception:
                if attempt == retries - 1:
                    self.health.record(
                        "migrate_transport_error", "site:kv_migrate",
                        step=self.ticks)
                    return None
                delay = min(cap, backoff * (2.0 ** attempt))
                delay *= 0.5 + self.health.uniform(
                    "migrate_backoff", self.ticks, attempt)
                time.sleep(delay)

    def _try_migrate_live(self, req, src: Replica, role) -> bool:
        """Migrate one RESIDENT row off ``src``: reserve landing pages
        at the best-scoring destination with room, ship the committed
        pages (everything below the cursor) in pool-native wire form,
        commit, release the source. Token-exact: the cursor survives
        the move and sampling is keyed ``(seed, rid, n_generated)``, so
        the stream continues as if it never moved. False = the row
        stays (priced against us, no destination room, or the wire
        failed) and finishes in place."""
        pslot = req.slot
        npg = role._pages_held(req.cursor)
        if npg == 0:
            # nothing committed yet: hand the request straight back to
            # the fleet queue instead of burning drain time on it
            if pslot is not None:
                role._free_slot(pslot)
            req.slot = None
            self.queue.append(req)
            self.queue = deque(sorted(self.queue,
                                      key=lambda r: r.arrival))
            self.stats.drain_requeued += 1
            return False
        wire_ms, reprefill_ms = self._price_migration(role, npg)
        if wire_ms >= reprefill_ms:
            self.stats.migration_refusals += 1
            return False
        cands = [r for r in self._route_candidates()
                 if r.index != src.index
                 and self.router.health_factor(
                     self.health.state(r.peer)) is not None]
        mean = (sum(r.load_ms() for r in cands) / len(cands)
                if cands else 0.0)
        cands.sort(key=lambda r: (
            -(self.router.score(r, req, self.health.state(r.peer),
                                mean) or 0.0),
            _u(self.seed, "migrate", req.rid, r.index)))
        for dst in cands:
            dst_role = dst.admit_role
            if dst_role.cfg.page != role.cfg.page:
                continue               # pages ship verbatim
            out = self.ops.migrate_live_core(
                req, role, dst_role, pslot, npg,
                lambda p, _d=dst_role: self._migrate_transport(p, _d))
            if out is None:
                continue               # no slot/pages there; try next
            if out is False:
                self.stats.migration_failures += 1
                self._log_event("migrate_failed", src.index,
                                f"rid={req.rid} dst={dst.index}")
                return False
            dslot, dpids = out
            self._warm_migrated_prefix(req, dst_role, dpids)
            sess = getattr(req, "session", None)
            if sess is not None:
                self.router.affinity[sess] = dst.index
            self._account_migration(role, npg, wire_ms, reprefill_ms)
            self._log_event(
                "migrate", src.index,
                f"rid={req.rid} pages={npg} -> replica {dst.index}")
            return True
        return False

    def _migrate_prefix(self, req, home_idx: int, dst: Replica) -> bool:
        """Spill-path migration: the request re-homed, but its prefix
        pages still live in the OLD home's pool (a draining, full, or
        outscored replica). Ship the resident full-page chain into
        destination CACHE pages — alloc, land, register under the same
        chain hashes, then release to the reclaimable cache — so
        admission at the new home attaches the pages instead of
        re-prefilling them. Priced like every migration; skipped
        whenever the wire loses."""
        from triton_distributed_tpu.serving.state import page_chain_hash

        if home_idx in self._dead or home_idx in self._retired \
                or home_idx >= len(self.replicas) \
                or home_idx == dst.index:
            return False
        src_role = self.replicas[home_idx].admit_role
        dst_role = dst.admit_role
        if not (src_role.pool.prefix_cache
                and dst_role.pool.prefix_cache):
            return False
        if src_role.cfg.page != dst_role.cfg.page:
            return False
        # cp-mismatched replicas shard their pools differently: a page
        # chain gathered in one layout does not land 1:1 in the other,
        # so the ship is refused here and admission re-prefills
        if getattr(src_role.pool, "cp", 1) \
                != getattr(dst_role.pool, "cp", 1):
            return False
        page = src_role.cfg.page
        seq = req.seq
        src_pids, hashes, h = [], [], 0
        for p in range((len(seq) - 1) // page):
            h = page_chain_hash(h, seq[p * page:(p + 1) * page])
            pg = src_role.pool.lookup(h, p)
            if pg is None:
                break
            src_pids.append(int(pg))
            hashes.append(h)
        npg = len(src_pids)
        if npg == 0 or dst.overlap_pages(req) >= npg:
            return False
        wire_ms, reprefill_ms = self._price_migration(src_role, npg)
        if wire_ms >= reprefill_ms:
            self.stats.migration_refusals += 1
            return False
        if npg > dst_role.pool.available - dst_role._committed_pages():
            return False
        dpids = [dst_role.pool.alloc(i) for i in range(npg)]
        if any(pg is None for pg in dpids):
            for pg in dpids:
                if pg is not None:
                    dst_role.pool.release(pg)
            return False
        payload = src_role.gather_pages(src_pids)
        shipped = self._migrate_transport(payload, dst_role)
        if shipped is None:
            for pg in dpids:
                dst_role.pool.release(pg)
            self.stats.migration_failures += 1
            self._log_event("migrate_failed", home_idx,
                            f"rid={req.rid} dst={dst.index}")
            return False
        dst_role.land_pages(dpids, *shipped)
        for pg, hh in zip(dpids, hashes):
            dst_role.pool.register(int(pg), hh)
        for pg in dpids:
            # refcount 0 + registered = reclaimable cache residency:
            # attachable by the arriving request, reclaimed under
            # pressure, never leaked
            dst_role.pool.release(int(pg))
        self._account_migration(src_role, npg, wire_ms, reprefill_ms)
        self._log_event(
            "migrate", home_idx,
            f"rid={req.rid} pages={npg} -> replica {dst.index} "
            f"(prefix)")
        return True

    def _account_migration(self, role, npg: int, wire_ms: float,
                           reprefill_ms: float) -> None:
        from triton_distributed_tpu.kernels.kv_ship import (
            ship_wire_bytes,
        )

        mc = role.model.config
        st = self.stats
        st.migrations += 1
        st.migrated_pages += npg
        st.migration_wire_bytes += ship_wire_bytes(
            npg, role.cfg.page, mc.n_kv_heads, mc.head_dim,
            mc.n_layers, getattr(mc, "kv_quant", None) is not None)
        st.migration_priced.append((wire_ms, reprefill_ms))

    def _warm_migrated_prefix(self, req, dst_role, dpids) -> None:
        """The landed pages below the cursor are frozen: register their
        chain hashes at the destination (the ``_warm_prefix_cache``
        discipline) so siblings sharing the prefix attach without
        another wire trip. Partial trailing pages stay private."""
        if not dst_role.pool.prefix_cache:
            return
        full = min(req.cursor // dst_role.cfg.page, len(dpids))
        if full <= 0:
            return
        hashes = dst_role._page_hashes(req, full)
        for p in range(full):
            dst_role.pool.register(int(dpids[p]), hashes[p])

    # ------------------------------------------------------ aggregates

    @property
    def prefix_hits(self) -> int:
        return self.stats.retired_prefix_hits + sum(
            role.stats.prefix_hits
            for r in self.replicas for role in r._roles
            if r.index not in self._dead)

    @property
    def evictions(self) -> int:
        return self.stats.retired_evictions + sum(
            role.stats.evictions
            for r in self.replicas for role in r._roles
            if r.index not in self._dead)

    @property
    def preemptions(self) -> int:
        return self.stats.retired_preemptions + sum(
            role.stats.preemptions
            for r in self.replicas for role in r._roles
            if r.index not in self._dead)

    def tenant_preemptions(self) -> dict:
        """tenant -> preemption count, live engines + retired."""
        out = dict(self.stats.retired_tenant_preemptions)
        for r in self.replicas:
            if r.index in self._dead:
                continue
            for role in r._roles:
                for t, n in role.stats.tenant_preemptions.items():
                    out[t] = out.get(t, 0) + n
        return out

    def per_tenant(self) -> dict:
        """:meth:`FleetStats.per_tenant` with the fleet's merged
        preemption map filled in — the one-call observability view."""
        return self.stats.per_tenant(self.tenant_preemptions())

    @property
    def generated_tokens(self) -> int:
        return sum(r["n"] for r in self.stats.records.values()
                   if r["completion_tick"] is not None)

    @property
    def goodput_tok_per_s(self) -> float:
        """Generated tokens of completed requests per MODELED wall
        second, where fleet wall = the SLOWEST replica's accumulated
        perf-model step time (replicas run concurrently on their own
        slices). Modeled, not measured: deterministic across runs, and
        it credits compute the router actually avoided — a prefix hit
        skips prefill chunks the model would otherwise bill. The
        measured host wall lives in ``stats.replica_time``."""
        wall = max(self.stats.replica_model_ms.values(), default=0.0)
        return self.generated_tokens / (wall / 1e3) if wall > 0 else 0.0

    def token_streams(self) -> dict:
        """rid -> completed token list (None while incomplete) — what
        the bench diffs against the fault-free reference run."""
        return {rid: rec["tokens"]
                for rid, rec in self.stats.records.items()}
