"""Process bootstrap and mesh construction.

Reference equivalent: ``utils.initialize_distributed`` (python/triton_dist/
utils.py:91-111) which reads RANK/WORLD_SIZE env, inits NCCL, then boots
NVSHMEM by broadcasting a unique id. On TPU the whole chain collapses into
``jax.distributed.initialize`` (multi-host rendezvous via the coordinator)
plus ``jax.devices()`` mesh discovery — symmetric memory needs no separate
runtime because every shard_map program allocates identically on every
device.
"""

from __future__ import annotations

import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)


@dataclass
class DistContext:
    """Handle describing this process's view of the distributed system."""

    mesh: Mesh
    rank: int                 # process index (host), not device index
    world_size: int           # number of processes
    num_devices: int          # global device count
    local_devices: tuple      # devices attached to this process
    axis_name: str = "x"

    @property
    def is_multihost(self) -> bool:
        return self.world_size > 1


_CONTEXT: DistContext | None = None


def initialize_distributed(
    axis_name: str = "x",
    mesh_shape: Sequence[int] | None = None,
    axis_names: Sequence[str] | None = None,
    seed: int | None = 42,
) -> DistContext:
    """Initialize the distributed runtime and build the default mesh.

    Multi-host: controlled by the standard JAX env vars
    (``COORDINATOR_ADDRESS``/``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``) which ``launch.sh`` sets; on a single host this is a
    no-op and the mesh covers the locally visible devices.

    Returns a :class:`DistContext`. Mirrors reference utils.py:91-111 but the
    bootstrap (NCCL pg + NVSHMEM uniqueid broadcast) is replaced by
    ``jax.distributed.initialize``.
    """
    global _CONTEXT
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    # Must run before any backend touch: jax.distributed.initialize has to
    # precede backend initialization, so the "already initialized" guard
    # checks the distributed client state, not jax.process_count().
    already = _distributed_initialized()
    if coord and nproc > 1 and not already:
        _initialize_with_retry(
            coord, nproc, int(os.environ.get("JAX_PROCESS_ID", "0"))
        )

    devices = jax.devices()
    if mesh_shape is None:
        mesh_devices = np.asarray(devices)
        mesh = Mesh(mesh_devices, (axis_name,))
    else:
        axis_names = tuple(axis_names or _default_axis_names(len(mesh_shape)))
        mesh_devices = np.asarray(devices).reshape(tuple(mesh_shape))
        mesh = Mesh(mesh_devices, axis_names)
        # keep ctx.axis_name pointing at a real axis of the mesh (the
        # last/innermost axis is the conventional comm axis)
        if axis_name not in axis_names:
            axis_name = axis_names[-1]

    ctx = DistContext(
        mesh=mesh,
        rank=jax.process_index(),
        world_size=jax.process_count(),
        num_devices=len(devices),
        local_devices=tuple(jax.local_devices()),
        axis_name=axis_name,
    )
    _CONTEXT = ctx
    if seed is not None:
        init_seed(ctx.rank, seed)
    return ctx


def _distributed_initialized() -> bool:
    """Is the jax distributed client up? ``jax.distributed.is_initialized``
    where it exists; older jax exposes only the global client state."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(
        getattr(jax, "_src", None), "distributed", None
    )
    return getattr(getattr(state, "global_state", None), "client", None) is not None


def _initialize_with_retry(
    coord: str,
    nproc: int,
    pid: int,
    *,
    retries: int | None = None,
    backoff: float | None = None,
    cap: float | None = None,
    sleep=time.sleep,
    initialize=None,
) -> None:
    """``jax.distributed.initialize`` with bounded exponential backoff.

    Multi-host rendezvous is the single flakiest step of a pod-scale
    launch: the coordinator process may simply not be listening yet
    (scheduler skew), or a transient DNS/conntrack blip drops the first
    connection. The reference framework retries nothing — one refused
    connection kills the whole job. Here each attempt backs off
    ``backoff * 2**attempt`` seconds (clamped to ``cap``) with ±50%
    jitter so restarting workers don't re-dogpile the coordinator, and
    the terminal failure names the coordinator address instead of
    surfacing the raw rendezvous exception from deep inside jax.

    Knobs (env): ``TDTPU_BOOTSTRAP_RETRIES`` (default 5 attempts),
    ``TDTPU_BOOTSTRAP_BACKOFF`` (base seconds, default 0.5),
    ``TDTPU_BOOTSTRAP_BACKOFF_CAP`` (default 8.0).
    """
    retries = retries if retries is not None else int(
        os.environ.get("TDTPU_BOOTSTRAP_RETRIES", "5")
    )
    backoff = backoff if backoff is not None else float(
        os.environ.get("TDTPU_BOOTSTRAP_BACKOFF", "0.5")
    )
    cap = cap if cap is not None else float(
        os.environ.get("TDTPU_BOOTSTRAP_BACKOFF_CAP", "8.0")
    )
    initialize = initialize or jax.distributed.initialize
    retries = max(int(retries), 1)
    last = None
    for attempt in range(retries):
        try:
            initialize(
                coordinator_address=coord,
                num_processes=nproc,
                process_id=pid,
            )
            return
        except Exception as e:                  # noqa: BLE001 — rendezvous
            last = e                            # errors surface as various
            if attempt == retries - 1:          # RuntimeError/XlaRuntimeError
                break                           # subclasses across jax versions
            delay = min(cap, backoff * (2.0 ** attempt))
            delay *= 0.5 + random.random()      # ±50% de-dogpile jitter
            logger.warning(
                "jax.distributed.initialize attempt %d/%d against "
                "coordinator %s failed (%s); retrying in %.2fs",
                attempt + 1, retries, coord, e, delay,
            )
            sleep(delay)
    try:
        from triton_distributed_tpu.runtime import health

        health.broadcast_signal(
            "bootstrap_exhausted", f"host:{pid}",
            detail=f"rendezvous with {coord!r} failed after {retries} "
                   f"attempt(s): {last}",
        )
    except Exception:           # the ledger must not mask the real error
        logger.exception("bootstrap: health broadcast failed")
    raise RuntimeError(
        f"jax.distributed.initialize failed after {retries} attempt(s) "
        f"rendezvousing with coordinator {coord!r} "
        f"(num_processes={nproc}, process_id={pid}). Check that the "
        "coordinator process is reachable on that address/port and that "
        "JAX_NUM_PROCESSES/JAX_PROCESS_ID are consistent across hosts. "
        f"Last error: {last}"
    ) from last


def _default_axis_names(ndim: int) -> tuple[str, ...]:
    base = ("dp", "pp", "tp", "sp", "ep")
    if ndim <= len(base):
        return base[:ndim]
    return tuple(f"ax{i}" for i in range(ndim))


def init_seed(rank: int, seed: int = 42) -> None:
    """Seed host-side RNGs deterministically per rank (reference utils.py:75-88)."""
    np.random.seed(seed + rank)
    try:
        import random

        random.seed(seed + rank)
    except Exception:
        pass


def get_context() -> DistContext:
    if _CONTEXT is None:
        return initialize_distributed()
    return _CONTEXT


def finalize_distributed() -> None:
    global _CONTEXT
    _CONTEXT = None
    if jax.process_count() > 1:
        jax.distributed.shutdown()
