"""Host-side deadline watchdog over collective launches.

A wedged collective on this stack does not crash — it *waits*: every
rank parks inside a semaphore wait whose credit never arrives (stalled
peer, dropped signal, io_callback worker-pool starvation on the CPU
interpreter — see ``config.ensure_interpreter_unblocked``). The default
observable is a silent hang that eats the whole CI budget.

The watchdog turns that into a bounded, diagnosable failure:

* :func:`collective_watchdog` is a context manager that ARMS a deadline.
  While armed, instrumented collective launches (``lang.launch``
  wraps the per-device callable when armed — arming participates in
  ``config.interp_key`` so cached builds rebuild with hooks) emit
  per-rank enter/exit heartbeats through host callbacks.
* A monitor thread watches the in-flight records. When a collective has
  been open longer than the deadline it **trips**: it captures rank-level
  diagnostics (which ranks entered, which never exited, expected vs
  observed semaphore credits derived from the heartbeats, the active
  fault plan), releases any fault-plan stall gates so a *gate-held* run
  can drain instead of wedging forever, and dumps the report to the log.
* On context exit the pending callbacks are flushed
  (``jax.effects_barrier``) and a trip raises :class:`WatchdogTimeout`
  with the full report — the "raise instead of hang" contract.

Scope and honesty: the watchdog can *unwedge* only stalls it owns (the
fault plan's host-side gates). A genuine device-side wedge — a lost DMA
on real hardware, a dropped barrier credit — cannot be cancelled from
the host; for those the watchdog still produces the diagnostic dump on
the monitor thread (the part a hang denies you), and
``TDTPU_WATCHDOG_KILL=1`` additionally hard-exits the process (exit
code 70) after a grace period so CI fails in seconds, not hours. The
test-suite equivalent is conftest's ``faulthandler`` deadline.

Host-loop runs (``tools/generate.py --watchdog-deadline``) arm the same
context around model build + decode so every instrumented collective in
the step loop is covered.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


class WatchdogTimeout(RuntimeError):
    """A collective exceeded the armed deadline (see the message for the
    rank/semaphore diagnostics captured at trip time)."""


@dataclass
class _Record:
    """One in-flight collective launch, assembled from rank heartbeats."""

    site: str
    collective_id: object
    n: int
    t_start: float
    entered: set = field(default_factory=set)
    gated: set = field(default_factory=set)     # ranks held by a stall gate
    exited: set = field(default_factory=set)

    @property
    def complete(self) -> bool:
        return len(self.exited) >= self.n

    def describe(self, deadline: float) -> str:
        missing_enter = sorted(set(range(self.n)) - self.entered)
        missing_exit = sorted(set(range(self.n)) - self.exited)
        # Heartbeat-derived semaphore view: a rank that entered has sent
        # its barrier credits to its peers; one that never exited never
        # consumed its final waits. Expected credits per rank on the
        # entry barrier: n-1; observed: ranks entered minus self.
        expected = self.n - 1
        observed = max(len(self.entered) - 1, 0)
        lines = [
            f"collective watchdog: deadline {deadline:.2f}s exceeded for "
            f"'{self.site}' (collective_id={self.collective_id}, "
            f"n={self.n}, open {time.monotonic() - self.t_start:.2f}s)",
            f"  ranks entered : {sorted(self.entered)} "
            f"(missing {missing_enter})",
            f"  ranks exited  : {sorted(self.exited)} "
            f"(missing {missing_exit})",
        ]
        if self.gated:
            lines.append(
                f"  stalled at fault-plan entry gate: rank "
                f"{sorted(self.gated)}"
            )
        lines.append(
            f"  barrier semaphore: expected {expected} credits/rank, "
            f"observed {observed} (from entry heartbeats)"
        )
        from triton_distributed_tpu.runtime import faults

        lines.append(f"  active fault plan: {faults.active_plan()!r}")
        return "\n".join(lines)


class CollectiveWatchdog:
    """Deadline monitor; use via :func:`collective_watchdog`."""

    def __init__(self, deadline: float = 10.0, poll: float = 0.02):
        self.deadline = float(deadline)
        self.poll = float(poll)
        self.trip_report: str | None = None
        self.tripped_records: list[_Record] = []
        self._records: list[_Record] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- heartbeats (io_callback worker threads) ---------------------------
    def on_enter(self, site, collective_id, n, me, step=None) -> None:
        me = int(me)
        with self._lock:
            rec = self._open_record(site, collective_id, n, me)
            rec.entered.add(me)
        from triton_distributed_tpu.runtime import faults

        plan = faults.active_plan()
        if plan is not None and me in plan.stalled_ranks(site, step):
            with self._lock:
                rec.gated.add(me)
            faults.stall_wait(site, me, step)
            with self._lock:
                rec.gated.discard(me)

    def on_exit(self, site, collective_id, n, me) -> None:
        me = int(me)
        with self._lock:
            for rec in self._records:
                if (
                    rec.site == site
                    and rec.collective_id == collective_id
                    and me in rec.entered
                    and me not in rec.exited
                ):
                    rec.exited.add(me)
                    break
            self._records = [r for r in self._records if not r.complete]

    def _open_record(self, site, collective_id, n, me) -> _Record:
        for rec in self._records:
            if (
                rec.site == site
                and rec.collective_id == collective_id
                and me not in rec.entered
            ):
                return rec
        rec = _Record(site, collective_id, n, time.monotonic())
        self._records.append(rec)
        return rec

    # -- monitor thread ----------------------------------------------------
    def _monitor(self):
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            with self._lock:
                expired = [
                    r for r in self._records
                    if now - r.t_start > self.deadline and not r.complete
                ]
                if not expired:
                    continue
                report = "\n".join(r.describe(self.deadline) for r in expired)
                self.trip_report = report
                self.tripped_records = list(expired)
            logger.error("%s", report)
            try:
                from triton_distributed_tpu.runtime import health

                health.notify_trip(report)
            except Exception:   # the ledger must never block the unwedge
                logger.exception("watchdog: health notification failed")
            from triton_distributed_tpu.runtime import faults

            # unwedge what we own: plan-injected stalls are host gates
            faults.release_stalls()
            if os.environ.get("TDTPU_WATCHDOG_KILL") == "1":
                time.sleep(max(self.deadline, 1.0))
                if any(not r.complete for r in self._records):
                    logger.critical(
                        "watchdog: collective still wedged after stall "
                        "release — hard-exiting (TDTPU_WATCHDOG_KILL=1)"
                    )
                    os._exit(70)
            return                      # one trip is terminal per arming

    # -- arming ------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._monitor, name="tdtpu-collective-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


_ARMED: CollectiveWatchdog | None = None
_LAST_TRIP: str | None = None


def armed() -> bool:
    """Is a watchdog armed? Folded into ``config.interp_key`` (via
    ``faults.trace_key``): arming must rebuild kernels with heartbeat
    instrumentation."""
    return _ARMED is not None


def current() -> CollectiveWatchdog | None:
    return _ARMED


def last_trip() -> str | None:
    """The most recent trip report (sticky across arming scopes) — the
    degradation layer's "watchdog tripped on a prior step" probe input.
    Cleared with :func:`clear_trip`."""
    return _LAST_TRIP


def clear_trip() -> None:
    global _LAST_TRIP
    _LAST_TRIP = None


# -- multi-slice trip aggregation -------------------------------------------
# Per-slice watchdogs see only their own heartbeats; a cross-slice hang
# trips on EVERY slice that was waiting. Each slice condenses its trip
# into a TripSummary, the summaries are exchanged over the DCN host
# channel (multislice.exchange_trip_summaries), and the merge names the
# actually-wedged slice: the one whose own ranks never exited (or sit on
# a stall gate), as opposed to slices that merely timed out waiting.

@dataclass(frozen=True)
class TripSummary:
    """One slice's condensed view of a watchdog trip (JSON-portable)."""

    slice_index: int
    site: str | None = None
    collective_id: str | None = None
    n: int = 0
    entered: tuple = ()
    exited: tuple = ()
    gated: tuple = ()
    open_s: float = 0.0

    @property
    def clean(self) -> bool:
        return self.site is None

    @property
    def wedged(self) -> bool:
        """Did THIS slice's ranks wedge (vs. merely waiting on a peer)?"""
        if self.clean:
            return False
        missing_exit = self.n - len(self.exited)
        return bool(self.gated) or missing_exit > 0

    def to_json(self) -> str:
        import json

        return json.dumps({
            "slice_index": self.slice_index, "site": self.site,
            "collective_id": self.collective_id, "n": self.n,
            "entered": list(self.entered), "exited": list(self.exited),
            "gated": list(self.gated), "open_s": self.open_s,
        })

    @staticmethod
    def from_json(text: str) -> "TripSummary":
        import json

        d = json.loads(text)
        return TripSummary(
            slice_index=int(d["slice_index"]), site=d.get("site"),
            collective_id=d.get("collective_id"), n=int(d.get("n", 0)),
            entered=tuple(d.get("entered", ())),
            exited=tuple(d.get("exited", ())),
            gated=tuple(d.get("gated", ())),
            open_s=float(d.get("open_s", 0.0)),
        )


def trip_summary(wd: CollectiveWatchdog, slice_index: int = 0) -> TripSummary:
    """Condense ``wd``'s trip (if any) into a :class:`TripSummary`. A
    watchdog that never tripped yields a clean summary — every slice
    contributes one so the exchange is collective."""
    recs = wd.tripped_records
    if not recs:
        return TripSummary(slice_index=slice_index)
    r = recs[0]
    return TripSummary(
        slice_index=slice_index, site=r.site,
        collective_id=repr(r.collective_id), n=r.n,
        entered=tuple(sorted(r.entered)), exited=tuple(sorted(r.exited)),
        gated=tuple(sorted(r.gated)),
        open_s=time.monotonic() - r.t_start,
    )


def merge_trip_summaries(summaries) -> tuple:
    """Merge per-slice trip summaries into one report naming the wedged
    slice(s). Returns ``(report_text, wedged_slice_indices)``."""
    summaries = sorted(summaries, key=lambda s: s.slice_index)
    tripped = [s for s in summaries if not s.clean]
    if not tripped:
        return ("multi-slice watchdog: no trips on any slice", ())
    wedged = tuple(s.slice_index for s in tripped if s.wedged)
    lines = ["multi-slice watchdog: merged trip report"]
    for s in summaries:
        if s.clean:
            lines.append(f"  slice {s.slice_index}: clean (no trip)")
            continue
        missing = sorted(set(range(s.n)) - set(s.exited))
        lines.append(
            f"  slice {s.slice_index}: tripped at '{s.site}' "
            f"(collective_id={s.collective_id}, n={s.n}, "
            f"open {s.open_s:.2f}s) missing-exit {missing} "
            f"gated {sorted(s.gated)}"
        )
    if wedged:
        lines.append(
            f"  verdict: wedged slice {list(wedged)} — ranks never "
            f"exited / held at a stall gate; other tripped slices were "
            f"waiting on it"
        )
    else:
        lines.append(
            "  verdict: no slice shows a local wedge — trips were "
            "deadline overruns only (deadline too tight, or the wedge "
            "cleared before the exchange)"
        )
    return ("\n".join(lines), wedged)


def report_merged_trip(summaries) -> str:
    """Merge summaries AND feed the verdict to the health ledgers: each
    wedged slice gets a fatal ``watchdog_trip`` signal under the peer key
    ``"slice:<k>"`` — the bridge from multi-slice diagnosis to mesh
    shrink (``topology.replan_mesh``)."""
    report, wedged = merge_trip_summaries(summaries)
    if wedged:
        from triton_distributed_tpu.runtime import health

        for k in wedged:
            health.broadcast_signal(
                "watchdog_trip", f"slice:{k}", detail=report)
    return report


# -- io_callback targets (module-level so traced closures stay tiny) --------

def _hb_enter(site, collective_id, n, me):
    import numpy as np

    wd = _ARMED
    if wd is not None:
        wd.on_enter(site, collective_id, n, me)
    else:
        # no watchdog: the stall gate still applies (plan semantics do
        # not depend on whether anyone is watching)
        from triton_distributed_tpu.runtime import faults

        faults.stall_wait(site, int(me))
    return np.int32(0)


def _hb_exit(site, collective_id, n, me, _dep):
    import numpy as np

    wd = _ARMED
    if wd is not None:
        wd.on_exit(site, collective_id, n, me)
    return np.int32(0)


class collective_watchdog:
    """``with collective_watchdog(deadline=2.0): ...`` — arm a deadline
    over every instrumented collective launched in the block. Raises
    :class:`WatchdogTimeout` at block exit if any collective overran
    (after flushing pending heartbeats via ``jax.effects_barrier``)."""

    def __init__(self, deadline: float = 10.0, poll: float = 0.02):
        self.deadline = deadline
        self.poll = poll
        self.wd: CollectiveWatchdog | None = None

    def __enter__(self) -> CollectiveWatchdog:
        global _ARMED
        if _ARMED is not None:
            raise RuntimeError("a collective watchdog is already armed")
        self.wd = CollectiveWatchdog(self.deadline, self.poll)
        _ARMED = self.wd
        self.wd.start()
        return self.wd

    def __exit__(self, exc_type, exc, tb):
        global _ARMED, _LAST_TRIP
        try:
            import jax

            jax.effects_barrier()
        except Exception:       # flushing is best-effort during unwind
            pass
        self.wd.stop()
        _ARMED = None
        if self.wd.trip_report is not None:
            _LAST_TRIP = self.wd.trip_report
            if exc_type is None:
                raise WatchdogTimeout(self.wd.trip_report)
        return False
