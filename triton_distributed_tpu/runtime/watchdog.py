"""Host-side deadline watchdog over collective launches.

A wedged collective on this stack does not crash — it *waits*: every
rank parks inside a semaphore wait whose credit never arrives (stalled
peer, dropped signal, io_callback worker-pool starvation on the CPU
interpreter — see ``config.ensure_interpreter_unblocked``). The default
observable is a silent hang that eats the whole CI budget.

The watchdog turns that into a bounded, diagnosable failure:

* :func:`collective_watchdog` is a context manager that ARMS a deadline.
  While armed, instrumented collective launches (``lang.launch``
  wraps the per-device callable when armed — arming participates in
  ``config.interp_key`` so cached builds rebuild with hooks) emit
  per-rank enter/exit heartbeats through host callbacks.
* A monitor thread watches the in-flight records. When a collective has
  been open longer than the deadline it **trips**: it captures rank-level
  diagnostics (which ranks entered, which never exited, expected vs
  observed semaphore credits derived from the heartbeats, the active
  fault plan), releases any fault-plan stall gates so a *gate-held* run
  can drain instead of wedging forever, and dumps the report to the log.
* On context exit the pending callbacks are flushed
  (``jax.effects_barrier``) and a trip raises :class:`WatchdogTimeout`
  with the full report — the "raise instead of hang" contract.

Scope and honesty: the watchdog can *unwedge* only stalls it owns (the
fault plan's host-side gates). A genuine device-side wedge — a lost DMA
on real hardware, a dropped barrier credit — cannot be cancelled from
the host; for those the watchdog still produces the diagnostic dump on
the monitor thread (the part a hang denies you), and
``TDTPU_WATCHDOG_KILL=1`` additionally hard-exits the process (exit
code 70) after a grace period so CI fails in seconds, not hours. The
test-suite equivalent is conftest's ``faulthandler`` deadline.

Host-loop runs (``tools/generate.py --watchdog-deadline``) arm the same
context around model build + decode so every instrumented collective in
the step loop is covered.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


class WatchdogTimeout(RuntimeError):
    """A collective exceeded the armed deadline (see the message for the
    rank/semaphore diagnostics captured at trip time)."""


@dataclass
class _Record:
    """One in-flight collective launch, assembled from rank heartbeats."""

    site: str
    collective_id: object
    n: int
    t_start: float
    entered: set = field(default_factory=set)
    gated: set = field(default_factory=set)     # ranks held by a stall gate
    exited: set = field(default_factory=set)

    @property
    def complete(self) -> bool:
        return len(self.exited) >= self.n

    def describe(self, deadline: float) -> str:
        missing_enter = sorted(set(range(self.n)) - self.entered)
        missing_exit = sorted(set(range(self.n)) - self.exited)
        # Heartbeat-derived semaphore view: a rank that entered has sent
        # its barrier credits to its peers; one that never exited never
        # consumed its final waits. Expected credits per rank on the
        # entry barrier: n-1; observed: ranks entered minus self.
        expected = self.n - 1
        observed = max(len(self.entered) - 1, 0)
        lines = [
            f"collective watchdog: deadline {deadline:.2f}s exceeded for "
            f"'{self.site}' (collective_id={self.collective_id}, "
            f"n={self.n}, open {time.monotonic() - self.t_start:.2f}s)",
            f"  ranks entered : {sorted(self.entered)} "
            f"(missing {missing_enter})",
            f"  ranks exited  : {sorted(self.exited)} "
            f"(missing {missing_exit})",
        ]
        if self.gated:
            lines.append(
                f"  stalled at fault-plan entry gate: rank "
                f"{sorted(self.gated)}"
            )
        lines.append(
            f"  barrier semaphore: expected {expected} credits/rank, "
            f"observed {observed} (from entry heartbeats)"
        )
        from triton_distributed_tpu.runtime import faults

        lines.append(f"  active fault plan: {faults.active_plan()!r}")
        return "\n".join(lines)


class CollectiveWatchdog:
    """Deadline monitor; use via :func:`collective_watchdog`."""

    def __init__(self, deadline: float = 10.0, poll: float = 0.02):
        self.deadline = float(deadline)
        self.poll = float(poll)
        self.trip_report: str | None = None
        self._records: list[_Record] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- heartbeats (io_callback worker threads) ---------------------------
    def on_enter(self, site, collective_id, n, me) -> None:
        me = int(me)
        with self._lock:
            rec = self._open_record(site, collective_id, n, me)
            rec.entered.add(me)
        from triton_distributed_tpu.runtime import faults

        plan = faults.active_plan()
        if plan is not None and me in plan.stalled_ranks(site):
            with self._lock:
                rec.gated.add(me)
            faults.stall_wait(site, me)
            with self._lock:
                rec.gated.discard(me)

    def on_exit(self, site, collective_id, n, me) -> None:
        me = int(me)
        with self._lock:
            for rec in self._records:
                if (
                    rec.site == site
                    and rec.collective_id == collective_id
                    and me in rec.entered
                    and me not in rec.exited
                ):
                    rec.exited.add(me)
                    break
            self._records = [r for r in self._records if not r.complete]

    def _open_record(self, site, collective_id, n, me) -> _Record:
        for rec in self._records:
            if (
                rec.site == site
                and rec.collective_id == collective_id
                and me not in rec.entered
            ):
                return rec
        rec = _Record(site, collective_id, n, time.monotonic())
        self._records.append(rec)
        return rec

    # -- monitor thread ----------------------------------------------------
    def _monitor(self):
        while not self._stop.wait(self.poll):
            now = time.monotonic()
            with self._lock:
                expired = [
                    r for r in self._records
                    if now - r.t_start > self.deadline and not r.complete
                ]
                if not expired:
                    continue
                report = "\n".join(r.describe(self.deadline) for r in expired)
                self.trip_report = report
            logger.error("%s", report)
            from triton_distributed_tpu.runtime import faults

            # unwedge what we own: plan-injected stalls are host gates
            faults.release_stalls()
            if os.environ.get("TDTPU_WATCHDOG_KILL") == "1":
                time.sleep(max(self.deadline, 1.0))
                if any(not r.complete for r in self._records):
                    logger.critical(
                        "watchdog: collective still wedged after stall "
                        "release — hard-exiting (TDTPU_WATCHDOG_KILL=1)"
                    )
                    os._exit(70)
            return                      # one trip is terminal per arming

    # -- arming ------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(
            target=self._monitor, name="tdtpu-collective-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


_ARMED: CollectiveWatchdog | None = None
_LAST_TRIP: str | None = None


def armed() -> bool:
    """Is a watchdog armed? Folded into ``config.interp_key`` (via
    ``faults.trace_key``): arming must rebuild kernels with heartbeat
    instrumentation."""
    return _ARMED is not None


def current() -> CollectiveWatchdog | None:
    return _ARMED


def last_trip() -> str | None:
    """The most recent trip report (sticky across arming scopes) — the
    degradation layer's "watchdog tripped on a prior step" probe input.
    Cleared with :func:`clear_trip`."""
    return _LAST_TRIP


def clear_trip() -> None:
    global _LAST_TRIP
    _LAST_TRIP = None


# -- io_callback targets (module-level so traced closures stay tiny) --------

def _hb_enter(site, collective_id, n, me):
    import numpy as np

    wd = _ARMED
    if wd is not None:
        wd.on_enter(site, collective_id, n, me)
    else:
        # no watchdog: the stall gate still applies (plan semantics do
        # not depend on whether anyone is watching)
        from triton_distributed_tpu.runtime import faults

        faults.stall_wait(site, int(me))
    return np.int32(0)


def _hb_exit(site, collective_id, n, me, _dep):
    import numpy as np

    wd = _ARMED
    if wd is not None:
        wd.on_exit(site, collective_id, n, me)
    return np.int32(0)


class collective_watchdog:
    """``with collective_watchdog(deadline=2.0): ...`` — arm a deadline
    over every instrumented collective launched in the block. Raises
    :class:`WatchdogTimeout` at block exit if any collective overran
    (after flushing pending heartbeats via ``jax.effects_barrier``)."""

    def __init__(self, deadline: float = 10.0, poll: float = 0.02):
        self.deadline = deadline
        self.poll = poll
        self.wd: CollectiveWatchdog | None = None

    def __enter__(self) -> CollectiveWatchdog:
        global _ARMED
        if _ARMED is not None:
            raise RuntimeError("a collective watchdog is already armed")
        self.wd = CollectiveWatchdog(self.deadline, self.poll)
        _ARMED = self.wd
        self.wd.start()
        return self.wd

    def __exit__(self, exc_type, exc, tb):
        global _ARMED, _LAST_TRIP
        try:
            import jax

            jax.effects_barrier()
        except Exception:       # flushing is best-effort during unwind
            pass
        self.wd.stop()
        _ARMED = None
        if self.wd.trip_report is not None:
            _LAST_TRIP = self.wd.trip_report
            if exc_type is None:
                raise WatchdogTimeout(self.wd.trip_report)
        return False
