"""Symmetric buffers: identically-shaped per-device arrays.

Reference equivalent: ``pynvshmem.nvshmem_create_tensor`` — a tensor
allocated at the same address on every PE's symmetric heap
(shmem/nvshmem_bind/pynvshmem/python/pynvshmem/__init__.py:94-160).

On TPU under shard_map the symmetric-memory property comes for free: a
global array sharded so every device holds one identical-shape shard IS a
symmetric buffer — Pallas refs to it on each device are the peer-visible
windows, and remote DMA addresses them by logical device id. This module
just packages the pattern.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class SymmetricBuffer:
    """A global array whose leading axis is sharded one-shard-per-device
    along ``axis`` of ``mesh``; ``local_shape`` is each device's window."""

    array: jax.Array
    mesh: Mesh
    axis: str

    @property
    def local_shape(self) -> tuple[int, ...]:
        n = self.mesh.shape[self.axis]
        return (self.array.shape[0] // n,) + tuple(self.array.shape[1:])

    @property
    def dtype(self):
        return self.array.dtype


def symm_zeros(mesh: Mesh, axis: str, local_shape, dtype=jnp.float32) -> SymmetricBuffer:
    n = mesh.shape[axis]
    global_shape = (n * local_shape[0],) + tuple(local_shape[1:])
    arr = jax.device_put(
        jnp.zeros(global_shape, dtype=dtype), NamedSharding(mesh, P(axis))
    )
    return SymmetricBuffer(arr, mesh, axis)


def symm_full(mesh: Mesh, axis: str, local_shape, fill_value, dtype=jnp.float32):
    n = mesh.shape[axis]
    global_shape = (n * local_shape[0],) + tuple(local_shape[1:])
    arr = jax.device_put(
        jnp.full(global_shape, fill_value, dtype=dtype), NamedSharding(mesh, P(axis))
    )
    return SymmetricBuffer(arr, mesh, axis)


def symm_empty(mesh: Mesh, axis: str, local_shape, dtype=jnp.float32):
    # XLA has no uninitialized alloc; zeros is the honest equivalent.
    return symm_zeros(mesh, axis, local_shape, dtype)
