"""Fail-loud guards over a compiled program's argument placements.

Two silent performance killers on a multi-chip mesh:

* **Involuntary resharding at a phase boundary** — a program compiled
  with parameter shardings that differ from the placements of the
  arrays the caller will actually pass (e.g. prefill producing KV
  caches in one layout while decode compiles wanting another). XLA
  "fixes" it with a full copy/reshard of the argument every call —
  cache-sized traffic per decode step at pod scale. The round-4
  dryrun's compile log caught exactly this by accident ("[SPMD]
  Involuntary full rematerialization" over the cache params); these
  guards make it a CI failure instead of a log tail.
* **A dropped donation** — a decode step whose cache arguments were
  donated but whose in/out placements diverged, so XLA allocates a
  fresh cache-sized buffer per step instead of aliasing in place (≡
  the reference kernels mutating their persistent caches,
  flash_decode.py:763-846).

Use with any ``jax.jit``-compiled callable::

    compiled = jitted.lower(*args).compile()
    assert_no_involuntary_resharding(compiled, args)
    aliased = input_output_aliased_params(compiled)

The checks read ``compiled.input_shardings`` and the optimized HLO
header, plus (best-effort) the executable's kept-argument set — jit
with the default ``keep_unused=False`` DROPS unused argument leaves
from the compiled signature, shifting parameter numbers.

IMPORTANT: lower the program from **abstract arguments carrying the
intended placements** (``jax.ShapeDtypeStruct(..., sharding=canon)``,
see ``Transformer.decode_abstract_args``), not from the live arrays —
a program lowered from committed arrays reports those arrays' own
shardings back, so a boundary check against it can never fail.
"""

from __future__ import annotations

import re

import jax


def _kept_indices(compiled, n_flat):
    """Flat argument-leaf indices that survived into the compiled
    signature, in HLO parameter order. jit(keep_unused=False) drops
    unused leaves; the executable records which (private attr,
    best-effort — absent means all kept)."""
    kept = getattr(
        getattr(compiled, "_executable", None), "_kept_var_idx", None
    )
    if kept is None:
        return list(range(n_flat))
    return sorted(kept)


def _leaf_pairs(compiled, args):
    """Flattened (path, arg leaf, compiled parameter sharding) triples
    over the KEPT argument leaves.

    ``compiled.input_shardings`` is a (args, kwargs) pair of pytrees
    mirroring the call signature after unused-leaf dropping; pairing it
    with the kept subset of the argument leaves lines every leaf up
    with the sharding the compiled program expects for it.
    """
    arg_sh, kw_sh = compiled.input_shardings
    assert not kw_sh, "keyword arguments are not supported by the guard"
    flat_args = jax.tree_util.tree_leaves_with_path(args)
    flat_sh = jax.tree_util.tree_leaves(
        arg_sh, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding)
    )
    kept = _kept_indices(compiled, len(flat_args))
    if len(kept) != len(flat_sh) or (kept and kept[-1] >= len(flat_args)):
        raise ValueError(
            f"argument tree ({len(flat_args)} leaves, {len(kept)} kept) "
            f"does not match the compiled signature ({len(flat_sh)} "
            "parameter shardings) — pass exactly the args the program "
            "was lowered with"
        )
    return [
        (jax.tree_util.keystr(flat_args[i][0]), flat_args[i][1], sh)
        for i, sh in zip(kept, flat_sh)
    ]


def find_involuntary_resharding(compiled, args, *, min_bytes=1 << 20):
    """List of (path, nbytes, arg sharding, program sharding) for every
    argument leaf of at least ``min_bytes`` whose current placement
    differs from the placement the compiled program expects — each one
    is a full reshard/copy XLA will silently insert at EVERY call."""
    bad = []
    for path, leaf, want in _leaf_pairs(compiled, args):
        if not isinstance(leaf, jax.Array) or leaf.nbytes < min_bytes:
            continue
        have = leaf.sharding
        if not have.is_equivalent_to(want, leaf.ndim):
            bad.append((path, leaf.nbytes, have, want))
    return bad


def assert_no_involuntary_resharding(compiled, args, *, min_bytes=1 << 20):
    """Fail loudly when calling ``compiled`` with ``args`` would
    reshard any argument of at least ``min_bytes`` (see
    :func:`find_involuntary_resharding`)."""
    bad = find_involuntary_resharding(compiled, args, min_bytes=min_bytes)
    if bad:
        lines = "\n".join(
            f"  {p} ({n} bytes): have {h.spec if hasattr(h, 'spec') else h}"
            f" -> program wants {w.spec if hasattr(w, 'spec') else w}"
            for p, n, h, w in bad
        )
        raise AssertionError(
            f"involuntary resharding of {len(bad)} argument(s) at every "
            f"call of this compiled program:\n{lines}\n"
            "Pin the producer's output shardings (or the consumer's "
            "in_shardings) so the placements agree across the boundary."
        )


_ALIAS_ENTRY = re.compile(
    r"\{([\d,\s]*)\}\s*:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may|must)-alias\)"
)


def _alias_table_text(text: str) -> str:
    """The brace-balanced body of the HLO header's
    ``input_output_alias={...}`` table ('' when absent) — the entries
    themselves contain nested ``{}`` so a regex-to-first-brace won't
    do."""
    key = "input_output_alias={"
    start = text.find(key)
    if start < 0:
        return ""
    i, depth = start + len(key), 1
    while i < len(text) and depth:
        depth += {"{": 1, "}": -1}.get(text[i], 0)
        i += 1
    return text[start + len(key):i - 1]


def input_output_aliased_params(compiled) -> dict:
    """Parse the optimized HLO header's ``input_output_alias`` table →
    ``{parameter_number: output_index_tuple}``. A donated argument that
    XLA actually aliases (updates in place) appears here; a donation
    XLA had to drop (placement/layout mismatch) does not."""
    out = {}
    for om in _ALIAS_ENTRY.finditer(_alias_table_text(compiled.as_text())):
        out_idx = tuple(
            int(t) for t in om.group(1).replace(" ", "").split(",") if t
        )
        out[int(om.group(2))] = out_idx
    return out


def leaf_range(args, selector) -> range:
    """Flat parameter-index range covered by ``selector(args)`` — e.g.
    ``leaf_range((params, caches, lens), lambda a: a[1])`` is the cache
    leaves' positions in the compiled program's parameter numbering
    (jit flattens positional args in order)."""
    flat_before = 0
    found = None
    target = selector(args)
    # walk the top-level args in order, counting leaves
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        if a is target:
            found = range(flat_before, flat_before + n)
        flat_before += n
    if found is None:
        raise ValueError("selector must return one of the top-level args")
    return found


def selfcheck() -> None:
    """Pin the jax/XLA introspection formats the guards depend on.

    The guards read two PRIVATE surfaces — the executable's
    ``_kept_var_idx`` set (which argument leaves survived
    ``keep_unused=False``) and the optimized HLO header's
    ``input_output_alias={...}`` table — and a jax/XLA version change
    renaming the attribute or reformatting the table would otherwise
    surface as spurious "dropped donation" / "resharding" errors on
    correct programs. This self-test runs two trivially known programs
    through the real pipeline and raises one CLEAR diagnostic when the
    expectations no longer hold (ADVICE r5); call it from CI (the
    test-suite does) or before trusting a guard verdict on a new jax.
    """
    import jax.numpy as jnp

    # 1) a donated, genuinely-aliasable argument must round-trip
    #    through input_output_aliased_params
    f = jax.jit(lambda s, x: (s + x, jnp.float32(0.0)), donate_argnums=(0,))
    x = jnp.zeros((128, 128), jnp.float32)
    y = jnp.ones((128, 128), jnp.float32)
    compiled = f.lower(x, y).compile()
    aliased = input_output_aliased_params(compiled)
    if 0 not in aliased:
        raise AssertionError(
            "shardguard.selfcheck: a trivially-donated jit argument did "
            "not appear in the parsed input_output_alias table "
            f"(got {aliased!r}) — the optimized-HLO header format has "
            "drifted; update shardguard._ALIAS_ENTRY/_alias_table_text "
            "before trusting assert_args_aliased on this jax"
        )
    if assert_args_aliased(compiled, (x, y), lambda a: a[0]) is not None:
        raise AssertionError("assert_args_aliased returned unexpectedly")

    # 2) an UNUSED argument leaf must be visibly dropped from the kept
    #    set (or all leaves reported kept — the documented best-effort
    #    fallback when the private attr is absent), and the kept/
    #    sharding pairing must stay consistent
    g = jax.jit(lambda used, unused: used * 2.0)
    compiled2 = g.lower(x, x).compile()
    kept = _kept_indices(compiled2, 2)
    flat_sh = jax.tree_util.tree_leaves(
        compiled2.input_shardings[0],
        is_leaf=lambda s: isinstance(s, jax.sharding.Sharding),
    )
    if len(kept) != len(flat_sh):
        raise AssertionError(
            "shardguard.selfcheck: the kept-argument set "
            f"({kept!r}) does not line up with the compiled parameter "
            f"shardings ({len(flat_sh)} entries) — the _kept_var_idx "
            "attribute has drifted; _leaf_pairs would misattribute "
            "shardings to the wrong leaves on this jax"
        )
    # the consistency check above is the load-bearing one; additionally
    # pin today's exact behavior so a silent semantic change is visible
    if kept not in ([0], [0, 1]):
        raise AssertionError(
            f"shardguard.selfcheck: unexpected kept set {kept!r} for a "
            "2-arg program with one unused arg"
        )


def assert_args_aliased(compiled, args, selector, *, min_bytes=0):
    """Assert every leaf of ``selector(args)`` (≥ ``min_bytes``) is
    input/output-aliased in ``compiled`` — i.e. its donation survived
    and the program updates it in place. A selected leaf the program
    dropped as unused also fails (a serving-state buffer the program
    never reads is its own bug)."""
    aliased = input_output_aliased_params(compiled)
    flat_n = len(jax.tree_util.tree_leaves(args))
    # flat leaf index → HLO parameter number (unused leaves dropped)
    param_of = {flat: p for p, flat in enumerate(_kept_indices(compiled, flat_n))}
    idxs = leaf_range(args, selector)
    leaves = jax.tree_util.tree_leaves(selector(args))
    missing = [
        i for i, leaf in zip(idxs, leaves)
        if getattr(leaf, "nbytes", 0) >= min_bytes
        and param_of.get(i) not in aliased
    ]
    if missing:
        raise AssertionError(
            f"argument leaves {missing} (of {list(idxs)}) are NOT input/"
            "output-aliased — their donation was dropped (or the leaf is "
            "unused), so the program copies them instead of updating in "
            "place. Check that the output placements equal the input "
            "placements (with_sharding_constraint) and that "
            "donate_argnums covers them."
        )
