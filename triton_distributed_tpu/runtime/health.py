"""Peer-health ledger: every failure signal the stack emits, one state
machine, three layers of action.

PR-1 built the *diagnosis* half of robustness: the fault-plan chaos
engine injects, the watchdog names the wedged rank, the degradation
layer demotes fused engines to their XLA twins. But the verdicts were
disconnected one-way latches — ``FaultPlan.unhealthy_peers`` had to be
hand-declared, ``stats.degraded`` never un-set, and a slice death
stranded whatever it was holding. The :class:`HealthLedger` closes the
loop: it AGGREGATES the signals the stack already produces —

* watchdog trip reports (per-rank enter/exit heartbeats,
  :mod:`triton_distributed_tpu.runtime.watchdog` — the monitor thread
  calls :func:`notify_trip` at trip time, before it releases the stall
  gates, so a caller blocked on a gated transport observes the verdict
  the moment it unblocks);
* bootstrap retry exhaustion (:mod:`runtime.bootstrap` broadcasts a
  ``bootstrap_exhausted`` signal before raising);
* transport/kernel exceptions from the serving engines
  (``DisaggregatedEngine._run_transport``, ``ServingEngine`` device
  failures);
* chaos-injected signals (``SliceDeath`` replay, tests);

— into a per-peer state machine::

      healthy ──failure──▶ suspect ──2nd failure──▶ unhealthy
         ▲                    │                        │
         │              clean×suspect_clears     clean×probation_after
         │                    ▼                        ▼
         └────────────────(healthy)      probation ──probe ok×promote_after──▶ healthy
                                              │
                                          probe fail ──▶ unhealthy

FATAL signals (:data:`FATAL_KINDS`: a slice death, a watchdog trip, a
kernel exception, rendezvous exhaustion) jump straight to ``unhealthy``;
soft signals (a transport error that retries absorbed) walk through
``suspect``. Probes are SEEDED and deterministic: :meth:`probe_due`
fires on a crc32-phased step schedule, so two replays of the same trace
probe at the same ticks — the property the determinism test asserts.

Peer keys: collective ranks are plain ``int``s (these feed
``FaultPlan.unhealthy_peers`` via :meth:`to_fault_plan` and the mesh
shrink via :func:`runtime.topology.replan_mesh`); slices are
``"slice:<k>"``; engine-level sites are ``"site:<name>"``
(``site:kv_ship`` = the DCN ship wire, ``site:serving_step`` = the
serving kernel path).

Ledger instances register in a module-level weak set so out-of-band
reporters (the watchdog monitor thread, bootstrap) can
:func:`broadcast_signal` without plumbing a handle through every layer;
:func:`get_ledger` lazily owns a process-default instance for code with
no engine in scope.
"""

from __future__ import annotations

import enum
import logging
import re
import threading
import weakref
import zlib
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


class PeerState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    UNHEALTHY = "unhealthy"
    PROBATION = "probation"


#: signal kinds that jump a peer straight to UNHEALTHY — verdicts, not
#: hints: a tripped watchdog already waited out a full deadline, a slice
#: death and a rendezvous exhaustion are not ambiguous, and a kernel
#: exception means the device path is broken NOW (the engine re-runs the
#: batch on its XLA twin either way; probation decides when to re-trust).
FATAL_KINDS = frozenset({
    "slice_death", "replica_death", "watchdog_trip",
    "bootstrap_exhausted", "kernel_error",
})


@dataclass(frozen=True)
class HealthSignal:
    """One recorded failure signal (the ledger keeps a bounded tail per
    peer for the snapshot/report path)."""

    kind: str
    peer: object
    step: int | None = None
    detail: str = ""


@dataclass
class _PeerHealth:
    state: PeerState = PeerState.HEALTHY
    strikes: int = 0        # lifetime-ish failure count; reset on promotion
    cleans: int = 0         # consecutive clean observations in this state
    probes_ok: int = 0      # consecutive successful probes in probation
    signals: list = field(default_factory=list)


class HealthLedger:
    """The per-peer / per-slice health state machine (module docstring).

    All thresholds are constructor knobs so tests can tighten them;
    defaults are tuned for serving traces (a probe every ~4 engine
    steps, two clean probes to re-trust a wire).

    Thread-safe: the watchdog monitor thread records concurrently with
    the engine's host loop.
    """

    def __init__(self, seed: int = 0, *, suspect_clears: int = 2,
                 unhealthy_after: int = 2, probation_after: int = 3,
                 promote_after: int = 2, probe_interval: int = 4,
                 max_signals: int = 256):
        self.seed = int(seed)
        self.suspect_clears = int(suspect_clears)
        self.unhealthy_after = int(unhealthy_after)
        self.probation_after = int(probation_after)
        self.promote_after = int(promote_after)
        self.probe_interval = max(int(probe_interval), 1)
        self.max_signals = int(max_signals)
        self._peers: dict = {}
        self._lock = threading.RLock()
        _LEDGERS.add(self)

    # -- determinism core ---------------------------------------------------

    def uniform(self, *key) -> float:
        """Deterministic uniform in [0, 1) from (seed, *key) — the
        fault engine's crc32 trick (stable across processes, unlike
        ``hash``). Shared by the probe schedule and the ship-retry
        backoff jitter."""
        h = zlib.crc32(repr((self.seed,) + key).encode())
        return h / 2.0 ** 32

    def _phase(self, peer) -> int:
        return int(self.uniform("probe_phase", peer) * self.probe_interval)

    # -- signal ingestion ---------------------------------------------------

    def _entry(self, peer) -> _PeerHealth:
        p = self._peers.get(peer)
        if p is None:
            p = self._peers[peer] = _PeerHealth()
        return p

    def record(self, kind: str, peer, step: int | None = None,
               detail: str = "", fatal: bool | None = None) -> PeerState:
        """Ingest one failure signal for ``peer``; returns its new
        state. ``fatal`` overrides the :data:`FATAL_KINDS` default."""
        fatal = (kind in FATAL_KINDS) if fatal is None else bool(fatal)
        with self._lock:
            p = self._entry(peer)
            p.signals.append(HealthSignal(kind, peer, step, detail[:500]))
            del p.signals[:-self.max_signals]
            p.cleans = 0
            p.probes_ok = 0
            p.strikes += 1
            old = p.state
            if fatal or p.strikes >= self.unhealthy_after \
                    or p.state is PeerState.PROBATION:
                p.state = PeerState.UNHEALTHY
            elif p.state is PeerState.HEALTHY:
                p.state = PeerState.SUSPECT
            if p.state is not old:
                logger.warning(
                    "health: peer %r %s -> %s on %s%s", peer, old.value,
                    p.state.value, kind,
                    f" (step {step})" if step is not None else "",
                )
            return p.state

    def observe_clean(self, peer, step: int | None = None) -> PeerState:
        """Ingest one clean observation (a successful step/ship on the
        degraded path). SUSPECT clears back to HEALTHY after
        ``suspect_clears``; UNHEALTHY earns PROBATION after
        ``probation_after``; PROBATION promotes only through probes."""
        with self._lock:
            p = self._peers.get(peer)
            if p is None or p.state is PeerState.HEALTHY:
                return PeerState.HEALTHY
            p.cleans += 1
            if p.state is PeerState.SUSPECT \
                    and p.cleans >= self.suspect_clears:
                p.state = PeerState.HEALTHY
                p.cleans = 0
            elif p.state is PeerState.UNHEALTHY \
                    and p.cleans >= self.probation_after:
                p.state = PeerState.PROBATION
                p.cleans = 0
                p.probes_ok = 0
            return p.state

    # -- probes -------------------------------------------------------------

    def probe_due(self, peer, step) -> bool:
        """Should ``step`` run a seeded probe of ``peer``'s fused/wire
        path? True only in PROBATION, on a deterministic schedule: every
        ``probe_interval`` steps at a crc32 phase of (seed, peer) — two
        replays of the same trace probe at the same steps."""
        with self._lock:
            p = self._peers.get(peer)
            if p is None or p.state is not PeerState.PROBATION:
                return False
        return (int(step) + self._phase(peer)) % self.probe_interval == 0

    def probe_result(self, peer, ok: bool, step: int | None = None
                     ) -> PeerState:
        """Outcome of a probe step: ``promote_after`` consecutive clean
        probes re-promote to HEALTHY (strikes forgiven); one failed
        probe falls back to UNHEALTHY."""
        with self._lock:
            p = self._entry(peer)
            if not ok:
                p.signals.append(
                    HealthSignal("probe_failed", peer, step)
                )
                del p.signals[:-self.max_signals]
                p.state = PeerState.UNHEALTHY
                p.cleans = 0
                p.probes_ok = 0
                return p.state
            if p.state is not PeerState.PROBATION:
                return p.state
            p.probes_ok += 1
            if p.probes_ok >= self.promote_after:
                p.state = PeerState.HEALTHY
                p.strikes = 0
                p.cleans = 0
                p.probes_ok = 0
                logger.info("health: peer %r re-promoted to healthy "
                            "after %d clean probe(s)", peer,
                            self.promote_after)
            return p.state

    # -- queries ------------------------------------------------------------

    def state(self, peer) -> PeerState:
        with self._lock:
            p = self._peers.get(peer)
            return PeerState.HEALTHY if p is None else p.state

    def peers(self) -> dict:
        with self._lock:
            return {k: v.state for k, v in self._peers.items()}

    def unhealthy_peers(self) -> tuple:
        """UNHEALTHY collective ranks (int peer keys), sorted — the
        tuple :meth:`to_fault_plan` feeds into
        ``FaultPlan.unhealthy_peers`` automatically."""
        with self._lock:
            return tuple(sorted(
                k for k, v in self._peers.items()
                if isinstance(k, int) and v.state is PeerState.UNHEALTHY
            ))

    def unhealthy_slices(self) -> tuple:
        """UNHEALTHY slice indices (``"slice:<k>"`` peer keys), sorted."""
        with self._lock:
            out = []
            for k, v in self._peers.items():
                if (isinstance(k, str) and k.startswith("slice:")
                        and v.state is PeerState.UNHEALTHY):
                    out.append(int(k.split(":", 1)[1]))
            return tuple(sorted(out))

    def snapshot(self) -> dict:
        """Reporting view: peer -> {state, strikes, last signal kind}."""
        with self._lock:
            return {
                str(k): {
                    "state": v.state.value,
                    "strikes": v.strikes,
                    "signals": len(v.signals),
                    "last": v.signals[-1].kind if v.signals else None,
                }
                for k, v in self._peers.items()
            }

    def to_fault_plan(self, base=None):
        """A :class:`~triton_distributed_tpu.runtime.faults.FaultPlan`
        with ``unhealthy_peers`` filled from the ledger (merged with
        ``base``'s, faults preserved) — the hand-declared field, now
        automatic."""
        from dataclasses import replace

        from triton_distributed_tpu.runtime.faults import FaultPlan

        base = base if base is not None else FaultPlan(seed=self.seed)
        merged = tuple(sorted(
            set(base.unhealthy_peers) | set(self.unhealthy_peers())
        ))
        return replace(base, unhealthy_peers=merged)

    # -- watchdog trip ingestion -------------------------------------------

    _RE_SITE = re.compile(
        r"deadline [\d.]+s exceeded for '([^']+)' \(collective_id=.*?"
        r"n=(\d+)", re.S)
    _RE_MISSING_EXIT = re.compile(r"ranks exited\s*:\s*\[[^\]]*\]\s*"
                                  r"\(missing \[([^\]]*)\]\)")
    _RE_GATED = re.compile(r"stalled at fault-plan entry gate: rank "
                           r"\[([^\]]*)\]")

    def ingest_trip_report(self, report: str) -> None:
        """Parse a watchdog trip report (``_Record.describe`` text —
        possibly several blocks) into ledger signals: the tripped SITE
        becomes an unhealthy ``site:<name>`` peer, and on multi-rank
        collectives every rank that never exited (or sat on a stall
        gate) is recorded as an unhealthy int rank. Single-participant
        host instruments (n=1: the serving step, a kv_ship transport)
        only mark the site — their "rank 0" is the host, not a mesh
        peer."""
        blocks = report.split("collective watchdog: ")
        for block in blocks:
            m = self._RE_SITE.search(block)
            if m is None:
                continue
            site, n = m.group(1), int(m.group(2))
            self.record("watchdog_trip", f"site:{site}",
                        detail=block[:500])
            if n <= 1:
                continue
            ranks: set = set()
            for rx in (self._RE_MISSING_EXIT, self._RE_GATED):
                mm = rx.search(block)
                if mm and mm.group(1).strip():
                    ranks.update(
                        int(x) for x in mm.group(1).split(",")
                        if x.strip()
                    )
            for r in sorted(ranks):
                self.record("watchdog_trip", r, detail=f"site {site}")


# ---------------------------------------------------------- module registry

_LEDGERS: "weakref.WeakSet[HealthLedger]" = weakref.WeakSet()
_DEFAULT: HealthLedger | None = None
_DEFAULT_LOCK = threading.Lock()


def get_ledger() -> HealthLedger:
    """The process-default ledger (lazily created) — for reporters with
    no engine in scope (bootstrap, tools)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = HealthLedger()
        return _DEFAULT


def set_ledger(ledger: HealthLedger | None) -> None:
    """Replace (or, with None, drop) the process-default ledger."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = ledger


def reset_ledger() -> HealthLedger:
    """Fresh process-default ledger (test isolation)."""
    set_ledger(None)
    return get_ledger()


def live_ledgers() -> tuple:
    return tuple(_LEDGERS)


def broadcast_signal(kind: str, peer, step: int | None = None,
                     detail: str = "", fatal: bool | None = None) -> None:
    """Record a signal into EVERY live ledger — the out-of-band
    reporters' entry point (watchdog monitor thread, bootstrap,
    multi-slice merge): they cannot know which engine's ledger cares,
    and a ledger that never hears about its own peers is no ledger."""
    for led in live_ledgers():
        try:
            led.record(kind, peer, step=step, detail=detail, fatal=fatal)
        except Exception:
            logger.exception("health: broadcast to %r failed", led)


def notify_trip(report: str) -> None:
    """Watchdog trip hook: fan a trip report out to every live ledger
    (called from the monitor thread BEFORE stall gates release)."""
    for led in live_ledgers():
        try:
            led.ingest_trip_report(report)
        except Exception:
            logger.exception("health: trip ingestion into %r failed", led)
