"""Runtime: bootstrap, mesh/topology discovery.

TPU-native replacement for the reference's L0+L2 layers: ``pynvshmem``
symmetric-memory management (reference: shmem/nvshmem_bind/pynvshmem/python/
pynvshmem/__init__.py:94-196) and ``utils.initialize_distributed``
(reference: python/triton_dist/utils.py:91-111).
"""

from triton_distributed_tpu.runtime.bootstrap import (
    DistContext,
    finalize_distributed,
    get_context,
    initialize_distributed,
)
from triton_distributed_tpu.runtime.faults import (
    Corrupt,
    Delay,
    FaultPlan,
    ReplicaDeath,
    SignalFault,
    SliceDeath,
    Stall,
    fault_plan,
    parse_plan,
    set_fault_plan,
)
from triton_distributed_tpu.runtime.health import (
    HealthLedger,
    HealthSignal,
    PeerState,
    broadcast_signal,
    get_ledger,
    reset_ledger,
    set_ledger,
)
from triton_distributed_tpu.runtime.watchdog import (
    TripSummary,
    WatchdogTimeout,
    collective_watchdog,
    merge_trip_summaries,
    report_merged_trip,
    trip_summary,
)
from triton_distributed_tpu.runtime.multislice import (
    create_hybrid_mesh,
    exchange_trip_summaries,
    is_dcn_axis,
    num_slices,
)
from triton_distributed_tpu.runtime.shardguard import (
    assert_args_aliased,
    assert_no_involuntary_resharding,
    find_involuntary_resharding,
    input_output_aliased_params,
)
from triton_distributed_tpu.runtime.topology import (
    AllGatherMethod,
    LinkKind,
    MeshReplan,
    TopologyInfo,
    auto_allgather_method,
    detect_topology,
    flat_device_id,
    mesh_axes_size,
    replan_mesh,
    ring_neighbors,
)

__all__ = [
    "DistContext",
    "initialize_distributed",
    "finalize_distributed",
    "get_context",
    "TopologyInfo",
    "AllGatherMethod",
    "LinkKind",
    "detect_topology",
    "auto_allgather_method",
    "mesh_axes_size",
    "ring_neighbors",
    "flat_device_id",
    "create_hybrid_mesh",
    "is_dcn_axis",
    "num_slices",
    "assert_no_involuntary_resharding",
    "assert_args_aliased",
    "find_involuntary_resharding",
    "input_output_aliased_params",
    "FaultPlan",
    "parse_plan",
    "Delay",
    "Stall",
    "SignalFault",
    "Corrupt",
    "fault_plan",
    "set_fault_plan",
    "SliceDeath",
    "ReplicaDeath",
    "collective_watchdog",
    "WatchdogTimeout",
    "TripSummary",
    "trip_summary",
    "merge_trip_summaries",
    "report_merged_trip",
    "exchange_trip_summaries",
    "HealthLedger",
    "HealthSignal",
    "PeerState",
    "broadcast_signal",
    "get_ledger",
    "set_ledger",
    "reset_ledger",
    "MeshReplan",
    "replan_mesh",
]
