"""Deterministic fault-plan engine for the distributed kernels.

The reference framework's whole robustness story is one knob: a random
comm-stream sleep gated by ``for_correctness`` (reference:
python/triton_dist/kernels/nvidia/allgather.py:72-77), mirrored here as
the global boolean ``config.chaos_delay``. A serving stack needs
strictly more, and needs it *reproducible*: a failed nightly chaos run
that cannot be replayed is noise, not signal.

A :class:`FaultPlan` is a seeded, declarative set of faults injected
through the hook points the kernels already have:

* :class:`Delay` — per-(rank, step) delay distributions at the existing
  ``chaos_delay`` call sites (in a ring collective the (rank, step)
  pair identifies the edge the delayed DMA travels). Replaces the
  all-ranks-same-cycles behaviour of ``config.chaos_delay`` with a
  seeded per-edge draw.
* :class:`Stall` — a single-peer stall: the named rank blocks on a
  HOST-side gate at collective entry (wired through
  ``lang.launch`` instrumentation), wedging every other rank inside
  its semaphore waits — the hung-collective scenario the watchdog
  (:mod:`triton_distributed_tpu.runtime.watchdog`) exists to detect.
  Gates are released by a watchdog trip, by plan deactivation, or by
  the ``TDTPU_STALL_TIMEOUT`` backstop.
* :class:`SignalFault` — dropped or duplicated semaphore increments at
  the ``lang.shmem.signal_op`` hook (a dropped barrier credit is a
  permanent wedge; a duplicated one is a premature release racing the
  payload). These model NIC/driver misbehaviour the TPU ICI fabric
  itself won't produce — they exist to exercise the watchdog and the
  race detector, not to pass correctness runs.
* :class:`Corrupt` — payload-word corruption: one element of a
  collective's in-flight payload is overwritten at a kernel-chosen
  hook point before the send. Deterministic under the seed, so a
  corrupted result is bit-identical across replays (the property the
  end-to-end determinism test asserts).

All trace-time decisions (which ranks delay, how long, which word is
corrupted) are pure functions of ``(plan.seed, site, rank, step)``, so
the same plan replays the same fault sequence. Plans participate in the
kernel trace-cache key via :func:`trace_key` (folded into
``config.interp_key``): activating, changing, or clearing a plan
invalidates cached kernel builds instead of silently reusing traces
with stale injections.

Usage::

    plan = FaultPlan(seed=7, faults=(Delay(site="allgather", jitter=0.5),))
    with fault_plan(plan):
        y = all_gather(x, mesh, "x")          # delays injected, seeded
"""

from __future__ import annotations

import logging
import os
import threading
import zlib
from contextlib import contextmanager
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

#: Site names used by the kernel hook points. "*" in a fault matches any.
#: The last five are HOST-level sites (serving-engine instrumentation,
#: ``lang.maybe_instrument(axis=None)``): the ragged serving kernel's
#: chaos hook, the jitted serving step, the disaggregated KV-ship
#: transport, the fleet router's dispatch loop (a stalled router is
#: a different outage than a stalled engine — every replica starves at
#: once), and the fleet's replica→replica KV-page migration wire (a
#: stalled migration must degrade to re-prefill, never wedge a drain).
#: Training adds the context-parallel attention rings (``cp_ring``:
#: ring KV-rotation + Ulysses a2a) and the wire-quantized gradient
#: rings (``grad_ring``: the EF reduce/gather duals and the trainer's
#: dp all-reduce) — the last collectives that could wedge silently.
#: ``preempt`` gates the multi-tenant priority-preemption body (a
#: chaos Stall there must not leak the victim's pages or wedge the
#: admitting tier).
SITES = (
    "allgather", "reduce_scatter", "all_to_all", "ag_gemm", "gemm_rs",
    "moe_dispatch", "flash_decode",
    "ragged_paged", "serving_step", "kv_ship", "router_dispatch",
    "kv_migrate", "preempt", "cp_ring", "grad_ring",
)


@dataclass(frozen=True)
class Delay:
    """Seeded in-kernel delay at a ``chaos_delay`` hook point.

    ``rank``/``step`` of None match all; ``cycles`` is the base delay,
    ``jitter`` the relative spread — the injected delay for (rank, step)
    is ``cycles * (1 - jitter + 2 * jitter * u)`` with ``u`` a
    deterministic uniform draw from (seed, site, rank, step).
    """

    site: str = "*"
    rank: int | None = None
    step: int | None = None
    cycles: int = 100_000
    jitter: float = 0.0


@dataclass(frozen=True)
class Stall:
    """Single-peer stall: ``rank`` blocks on a host gate at entry of the
    matching collective until released (watchdog trip / deactivation /
    ``TDTPU_STALL_TIMEOUT``).

    ``step`` of None stalls every entry (the kernel-side gates carry no
    step context, so only step-less stalls reach them). A step-bound
    stall is TRANSIENT: it only fires at host instruments that pass
    their step/tick (the serving engines' ``serving_step``/``kv_ship``
    sites) and only at that step — the "stalled ship that recovers"
    the probation machinery exists to re-promote after."""

    site: str = "*"
    rank: int = 0
    step: int | None = None


@dataclass(frozen=True)
class SignalFault:
    """Drop (``kind="drop"``) or duplicate (``kind="dup"``) the matching
    rank's outgoing semaphore increments at hooked signal sites."""

    site: str = "*"
    rank: int | None = None
    kind: str = "drop"


@dataclass(frozen=True)
class Corrupt:
    """Overwrite one payload word of ``rank``'s outgoing shard before
    the send: column ``word`` of the shard's first row gets ``value``."""

    site: str = "*"
    rank: int = 0
    step: int | None = None
    word: int = 0
    value: float = 1.0e9


@dataclass(frozen=True)
class SliceDeath:
    """Kill a whole serving slice at a tick: from ``step`` on, the
    :class:`~triton_distributed_tpu.serving.engine.DisaggregatedEngine`
    treats the role living on hybrid-mesh DCN index ``slice`` (0 =
    prefill, 1 = decode) as dead — a fatal ``slice_death`` health
    signal plus the failover re-queue of everything the slice held.
    No kernel hook consumes it; it is an ENGINE-level fault."""

    slice: int = 1
    step: int = 0


@dataclass(frozen=True)
class ReplicaDeath:
    """Kill a whole fleet replica at a tick: from ``step`` on, the
    :class:`~triton_distributed_tpu.serving.fleet.ServingFleet` treats
    replica ``replica`` — one complete engine (or disaggregated pair)
    on its own carved mesh slice — as dead: a fatal ``replica_death``
    health signal plus the router-driven drain of everything the
    replica held back onto the survivors. Like :class:`SliceDeath` it
    is an ENGINE-level fault: no kernel hook consumes it."""

    replica: int = 1
    step: int = 0


_FAULT_TYPES = (Delay, Stall, SignalFault, Corrupt, SliceDeath,
                ReplicaDeath)


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, declarative set of faults (see module docstring).

    ``unhealthy_peers`` carries no injection of its own: it marks ranks
    the degradation layer (``ops.overlap.with_fallback`` /
    ``ops.moe.ep_moe``) must treat as failed, demoting fused engines to
    their XLA-native equivalents.

    ``max_concurrent_stalls`` caps how many stall gates the plan may
    HOLD at once (None = unlimited). Every held gate parks an
    io_callback worker thread; on small hosts (2-vCPU CI runners) a big
    stall matrix can park the whole pool and the *interpreter itself*
    wedges (``config.ensure_interpreter_unblocked``). Stalls beyond the
    cap are skipped with a log line — the plan degrades to a sparser
    matrix instead of deadlocking the harness.
    """

    seed: int = 0
    faults: tuple = ()
    unhealthy_peers: tuple = ()
    max_concurrent_stalls: int | None = None

    def __post_init__(self):
        for f in self.faults:
            if not isinstance(f, _FAULT_TYPES):
                raise TypeError(f"not a fault: {f!r}")
            if isinstance(f, SignalFault) and f.kind not in ("drop", "dup"):
                raise ValueError(f"SignalFault.kind must be drop|dup: {f.kind!r}")

    # -- determinism core ---------------------------------------------------
    def _u(self, *key) -> float:
        """Deterministic uniform in [0, 1) from (seed, *key) — crc32 is
        stable across processes/platforms (unlike ``hash``)."""
        h = zlib.crc32(repr((self.seed,) + key).encode())
        return h / 2.0 ** 32

    @staticmethod
    def _site_match(fault_site: str, site: str | None) -> bool:
        if fault_site == "*":
            return True
        return site is not None and fault_site == site

    # -- trace-time queries (all pure in (seed, site, rank, step)) ----------
    def delay_cycles(self, site: str | None, step: int | None, n: int):
        """Per-rank injected delay cycles for this (site, step): a length-n
        tuple of ints (0 = no delay for that rank)."""
        out = []
        for r in range(n):
            cyc = 0
            for f in self.faults:
                if not isinstance(f, Delay):
                    continue
                if not self._site_match(f.site, site):
                    continue
                if f.rank is not None and f.rank != r:
                    continue
                if f.step is not None and step is not None and f.step != step:
                    continue
                u = self._u("delay", site, r, step)
                cyc = max(
                    cyc, int(f.cycles * (1.0 - f.jitter + 2.0 * f.jitter * u))
                )
            out.append(cyc)
        return tuple(out)

    def signal_factor(self, site: str | None, rank: int) -> int:
        """Multiplier on ``rank``'s outgoing signal increments at hooked
        sites: 1 = untouched, 0 = dropped, 2 = duplicated."""
        for f in self.faults:
            if isinstance(f, SignalFault) and self._site_match(f.site, site):
                if f.rank is None or f.rank == rank:
                    return 0 if f.kind == "drop" else 2
        return 1

    def corruption(self, site: str | None, rank: int, step: int | None = None):
        """(word, value) to stamp into ``rank``'s outgoing payload at this
        (site, step), or None."""
        for f in self.faults:
            if isinstance(f, Corrupt) and self._site_match(f.site, site):
                if f.rank != rank:
                    continue
                if f.step is not None and step is not None and f.step != step:
                    continue
                return f.word, f.value
        return None

    def stalled_ranks(self, site: str | None, step: int | None = None
                      ) -> tuple:
        """Ranks stalled at (site, step). Kernel gates call with
        ``step=None`` and see only step-less stalls (they have no step
        context to match a transient stall against); host instruments
        pass their engine step/tick and additionally pick up the
        step-bound ones."""
        out = set()
        for f in self.faults:
            if not isinstance(f, Stall) or not self._site_match(f.site, site):
                continue
            if f.step is None or (step is not None and f.step == step):
                out.add(f.rank)
        return tuple(sorted(out))

    def dead_slices(self, step: int | None = None) -> tuple:
        """Slice indices dead at ``step`` (every :class:`SliceDeath`
        whose death step has arrived; all of them when step is None)."""
        return tuple(sorted({
            f.slice for f in self.faults
            if isinstance(f, SliceDeath)
            and (step is None or f.step <= step)
        }))

    def dead_replicas(self, step: int | None = None) -> tuple:
        """Fleet-replica indices dead at ``step`` — the
        :class:`ReplicaDeath` twin of :meth:`dead_slices`."""
        return tuple(sorted({
            f.replica for f in self.faults
            if isinstance(f, ReplicaDeath)
            and (step is None or f.step <= step)
        }))

    def schedule(self, site: str, n: int, steps: int) -> tuple:
        """The fully materialized injection schedule for one collective:
        every (kind, rank, step, params) entry this plan would inject at
        ``site`` over ``steps`` ring steps on ``n`` ranks. Two plans with
        the same seed+faults produce identical schedules — the object the
        determinism test compares."""
        entries = []
        for s in range(steps):
            for r, cyc in enumerate(self.delay_cycles(site, s, n)):
                if cyc:
                    entries.append(("delay", r, s, cyc))
        for r in range(n):
            fac = self.signal_factor(site, r)
            if fac != 1:
                entries.append(("signal", r, None, fac))
            c = self.corruption(site, r)
            if c is not None:
                entries.append(("corrupt", r, None, c))
        for f in self.faults:
            if isinstance(f, Stall) and self._site_match(f.site, site):
                entries.append(("stall", f.rank, f.step, None))
        return tuple(entries)

    def key(self) -> tuple:
        """Hashable identity for trace caches (frozen dataclasses hash by
        value, so the plan itself is the key)."""
        return (self.seed, self.faults, self.unhealthy_peers,
                self.max_concurrent_stalls)


# ------------------------------------------------------------------ parsing

def parse_plan(text: str) -> FaultPlan:
    """Parse a nightly chaos line back into a :class:`FaultPlan` — the
    replay half of the determinism contract (a failed chaos run that
    cannot be replayed is noise). Two formats:

    * compact: ``"seed=7; Delay(site=allgather, rank=2, cycles=50000);
      Stall(site=ag_gemm, rank=3); max_concurrent_stalls=2"`` — the
      dataclass reprs minus the quotes;
    * JSON: ``{"seed": 7, "faults": [{"kind": "Delay", "site":
      "allgather", "cycles": 50000}], "max_concurrent_stalls": 2}``.
    """
    import json
    import re

    kinds = {c.__name__: c for c in _FAULT_TYPES}

    def coerce(v):
        if isinstance(v, str):
            v = v.strip().strip("'\"")
            for conv in (int, float):
                try:
                    return conv(v)
                except ValueError:
                    pass
            if v in ("None", "null"):
                return None
        return v

    text = text.strip()
    if text.startswith("{"):
        d = json.loads(text)
        faults = tuple(
            kinds[f.pop("kind")](**{k: coerce(v) for k, v in f.items()})
            for f in d.get("faults", ())
        )
        return FaultPlan(
            seed=int(d.get("seed", 0)),
            faults=faults,
            unhealthy_peers=tuple(d.get("unhealthy_peers", ())),
            max_concurrent_stalls=d.get("max_concurrent_stalls"),
        )

    seed, cap, faults = 0, None, []
    for seg in filter(None, (s.strip() for s in text.split(";"))):
        m = re.fullmatch(r"(\w+)\s*\(\s*(.*?)\s*\)", seg)
        if m:
            kind, body = m.group(1), m.group(2)
            if kind not in kinds:
                raise ValueError(f"unknown fault kind {kind!r} in {seg!r}")
            kw = {}
            for item in filter(None, (i.strip() for i in body.split(","))):
                k, _, v = item.partition("=")
                kw[k.strip()] = coerce(v)
            faults.append(kinds[kind](**kw))
            continue
        k, _, v = seg.partition("=")
        k = k.strip()
        if k == "seed":
            seed = int(coerce(v))
        elif k == "max_concurrent_stalls":
            cap = coerce(v)
        else:
            raise ValueError(f"cannot parse fault-plan segment {seg!r}")
    return FaultPlan(seed=seed, faults=tuple(faults),
                     max_concurrent_stalls=cap)


# ---------------------------------------------------------------- activation

_ACTIVE: FaultPlan | None = None
_GATES: dict = {}           # (site, rank) -> threading.Event
_GATES_LOCK = threading.Lock()


def active_plan() -> FaultPlan | None:
    return _ACTIVE


def trace_key() -> tuple:
    """The fault-engine contribution to ``config.interp_key``: the active
    plan's identity plus whether collective instrumentation (watchdog
    heartbeats / stall gates) must be traced in. Changing either must
    invalidate cached kernel builds."""
    from triton_distributed_tpu.runtime import watchdog

    return (
        _ACTIVE.key() if _ACTIVE is not None else None,
        watchdog.armed(),
    )


@contextmanager
def fault_plan(plan: FaultPlan):
    """Activate ``plan`` for the dynamic extent of the block. Nested
    activation is rejected (two overlapping plans have no defined
    composition). All stall gates are released on exit, so a plan can
    never wedge code outside its own scope."""
    global _ACTIVE
    if _ACTIVE is not None:
        raise RuntimeError(f"a fault plan is already active: {_ACTIVE}")
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = None
        release_stalls()


def set_fault_plan(plan: FaultPlan | None):
    """Imperative twin of :func:`fault_plan` for host loops that cannot
    scope a context manager (clears stall gates when deactivating)."""
    global _ACTIVE
    _ACTIVE = plan
    if plan is None:
        release_stalls()


# ---------------------------------------------------------------- stalls

def stall_timeout() -> float:
    """Backstop for a stall gate nobody releases (no watchdog armed):
    seconds before a stalled rank proceeds anyway."""
    return float(os.environ.get("TDTPU_STALL_TIMEOUT", "30"))


def _gate(site: str, rank: int) -> threading.Event:
    with _GATES_LOCK:
        return _GATES.setdefault((site, rank), threading.Event())


_HELD = 0        # stall gates currently held (guarded by _GATES_LOCK)


def held_stalls() -> int:
    """How many stall gates are currently parked on worker threads."""
    with _GATES_LOCK:
        return _HELD


def stall_wait(site: str, rank: int, step: int | None = None) -> None:
    """Host-side stall gate, called from the collective-entry heartbeat
    (runs on an io_callback worker thread, NOT the main thread). Blocks
    iff the active plan stalls ``rank`` at ``site`` — unless the plan's
    ``max_concurrent_stalls`` gates are already held, in which case the
    stall is SKIPPED (logged): a parked gate costs a worker thread, and
    exhausting the pool wedges the interpreter itself (ROADMAP: big
    stall matrices on 2-vCPU CI runners). Once an armed watchdog has
    tripped, further stalls are skipped too: the trip already released
    the gates, and re-parking after it would wedge the recovery path on
    the ``TDTPU_STALL_TIMEOUT`` backstop."""
    global _HELD
    plan = _ACTIVE
    if plan is None or rank not in plan.stalled_ranks(site, step):
        return
    from triton_distributed_tpu.runtime import watchdog

    wd = watchdog.current()
    if wd is not None and wd.trip_report is not None:
        return
    cap = plan.max_concurrent_stalls
    with _GATES_LOCK:
        if cap is not None and _HELD >= cap:
            logger.info(
                "fault plan stall (site=%s rank=%d) skipped: "
                "max_concurrent_stalls=%d gates already held",
                site, rank, cap,
            )
            return
        _HELD += 1
    ev = _gate(site, rank)
    try:
        if not ev.wait(timeout=stall_timeout()):
            logger.warning(
                "fault plan stall (site=%s rank=%d) hit the %.0fs "
                "TDTPU_STALL_TIMEOUT backstop with no watchdog release",
                site, rank, stall_timeout(),
            )
    finally:
        with _GATES_LOCK:
            _HELD -= 1


def release_stalls() -> None:
    """Release every stall gate (watchdog trip / plan deactivation)."""
    with _GATES_LOCK:
        for ev in _GATES.values():
            ev.set()
        _GATES.clear()


# ------------------------------------------------------- trace-time injectors
# Called from INSIDE Pallas kernel bodies at trace time. They emit
# rank-conditional Mosaic ops (pl.when on the traced rank index), so one
# SPMD trace carries every rank's faults.

def inject_delay(site, step, me, n, base_cycles) -> bool:
    """Inject the active plan's delays at a ``chaos_delay`` hook point.
    Returns False when no plan is active (legacy ``config.chaos_delay``
    behaviour applies); True when the plan handled the site (possibly
    injecting nothing)."""
    plan = _ACTIVE
    if plan is None:
        return False
    from jax.experimental import pallas as pl

    if n is None:
        # hook site without rank context: only uniform (rank=None) faults
        cyc = plan.delay_cycles(site, step, 1)[0]
        if cyc:
            pl.delay(cyc)
        return True
    table = plan.delay_cycles(site, step, n)
    if not any(table):
        return True
    if len(set(table)) == 1 or me is None:
        pl.delay(max(table))
        return True
    for r, cyc in enumerate(table):
        if not cyc:
            continue

        @pl.when(me == r)
        def _(cyc=cyc):
            pl.delay(cyc)

    return True


def inject_signal(sem, inc, pe, site, me, n) -> bool:
    """Apply drop/dup signal faults at a ``signal_op`` hook point.
    Returns True when the plan emitted the (possibly faulted) signals
    itself; False when the caller should signal normally."""
    plan = _ACTIVE
    if plan is None or site is None or me is None or n is None:
        return False
    factors = [plan.signal_factor(site, r) for r in range(n)]
    if all(f == 1 for f in factors):
        return False
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def emit(times):
        for _ in range(times):
            if pe is None:
                pltpu.semaphore_signal(sem, inc=inc)
            else:
                pltpu.semaphore_signal(
                    sem, inc=inc, device_id=pe,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

    for r, fac in enumerate(factors):

        @pl.when(me == r)
        def _(fac=fac):
            emit(fac)

    return True


def maybe_corrupt(ref, site, me, n, *, row_off=0, step=None) -> None:
    """Stamp the plan's corruption (if any) into ``ref``: for each rank r
    with a matching :class:`Corrupt`, word ``fault.word`` of row
    ``row_off`` (this rank's outgoing shard head) is overwritten. No-op
    without an active plan."""
    plan = _ACTIVE
    if plan is None or n is None:
        return
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ncols = ref.shape[-1]
    for r in range(n):
        c = plan.corruption(site, r, step)
        if c is None:
            continue
        word, value = c
        col = word % ncols

        @pl.when(me == r)
        def _(col=col, value=value):
            ref[pl.ds(row_off, 1), pl.ds(col, 1)] = jnp.full(
                (1, 1), value, ref.dtype
            )
