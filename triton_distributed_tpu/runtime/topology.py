"""Topology discovery and communication-method selection.

Reference equivalent: the NVLink-fullmesh / NUMA / PCIe probes in
python/triton_dist/utils.py:504-786 and the ``AllGatherMethod`` auto
selection in python/triton_dist/kernels/nvidia/allgather.py:44-69.

On TPU the relevant facts are different: chips sit on a 2D/3D torus (ICI)
inside a slice, and slices are joined over DCN. Rings are the *natural*
method on a torus, full-mesh push is not. We classify each mesh axis as
ICI (same slice) or DCN (cross-slice / cross-host on CPU) and pick ring
variants accordingly.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh


class AllGatherMethod(enum.Enum):
    """Mirror of the reference's AllGatherMethod enum (allgather.py:44-56),
    re-ranged for TPU: rings over ICI; LL-packed for small messages; XLA
    collective fallback for DCN legs."""

    RING_1D = "ring_1d"
    RING_BIDIR = "ring_bidir"
    LL_SMALL = "ll_small"          # low-latency push, small messages
    # barrier-free LL over a persistent double-buffered workspace
    # (stateful: eager calls only — falls back to LL_SMALL in a trace)
    LL_PERSIST = "ll_persist"
    XLA_FALLBACK = "xla"           # lax.all_gather (DCN or no-pallas path)


class LinkKind(enum.Enum):
    ICI = "ici"       # within-slice torus links
    DCN = "dcn"       # across slices / hosts
    HOST = "host"     # CPU simulation


@dataclass
class TopologyInfo:
    num_devices: int
    link_kind: LinkKind
    is_torus: bool
    coords: tuple | None = None   # per-device coords when available


def slice_id(device) -> int:
    """The ICI-slice a device belongs to (0 when the backend doesn't
    report one). The single definition of "what counts as a slice" —
    used by both DCN classification here and hybrid-mesh construction
    (runtime/multislice.py)."""
    return getattr(device, "slice_index", 0) or 0


def detect_topology(mesh: Mesh, axis: str | None = None) -> TopologyInfo:
    """Classify the links along ``axis`` of ``mesh`` (whole mesh if None).

    Only the devices that communicate along ``axis`` (one line of the mesh,
    other coordinates fixed at 0) are inspected, so e.g. a cross-slice
    ``dp`` axis doesn't poison the classification of a within-slice ``tp``
    axis."""
    if axis is None:
        devices = mesh.devices.ravel()
    else:
        ax = mesh.axis_names.index(axis)
        index = tuple(slice(None) if i == ax else 0 for i in range(mesh.devices.ndim))
        devices = np.asarray(mesh.devices[index]).ravel()
    n = devices.size
    first = devices[0]
    if first.platform != "tpu":
        return TopologyInfo(num_devices=n, link_kind=LinkKind.HOST, is_torus=False)
    # All devices on one process/slice → ICI. Devices with distinct
    # slice_index (multi-slice) → DCN on the crossing axis.
    slice_ids = {slice_id(d) for d in devices}
    coords = tuple(getattr(d, "coords", None) for d in devices)
    if len(slice_ids) > 1:
        return TopologyInfo(n, LinkKind.DCN, is_torus=False, coords=coords)
    return TopologyInfo(n, LinkKind.ICI, is_torus=True, coords=coords)


def auto_allgather_method(
    topo: TopologyInfo, nbytes_per_shard: int, small_msg_threshold: int = 1 << 16
) -> AllGatherMethod:
    """Pick an AG method from topology + message size (≡ allgather.py:54-69)."""
    if topo.link_kind == LinkKind.DCN:
        return AllGatherMethod.XLA_FALLBACK
    if nbytes_per_shard <= small_msg_threshold:
        return AllGatherMethod.LL_SMALL
    if topo.num_devices >= 4:
        return AllGatherMethod.RING_BIDIR
    return AllGatherMethod.RING_1D


def auto_allgather_wire(
    nbytes_per_shard: int, threshold: int = 1 << 18
) -> str | None:
    """Wire dtype for a standalone AG ring when the caller says 'auto'
    (the wire twin of :func:`auto_allgather_method`): 'fp8' above the
    byte threshold, None below it.

    A standalone gather is pure comm, so compression always shortens the
    transfer — the gate is the fixed cost side: below ~256 KiB/shard the
    ring is latency-bound (the LL-push regime) and the quantize /
    dequantize passes plus the second scale-rail DMA per hop cost more
    than the saved wire time. int8 is never auto-picked: same bytes as
    fp8, strictly worse numerics (an explicit int8 wire is for int8-MXU
    consumers). The fused engines make the richer compute-vs-comm call
    in ``tune.perf_model.auto_wire_dtype``."""
    return "fp8" if nbytes_per_shard >= threshold else None


def mesh_axes_size(mesh, axes) -> int:
    """Product of mesh extents over ``axes`` (e.g. total DP degree)."""
    size = 1
    for a in axes:
        size *= mesh.shape[a]
    return size


def ring_neighbors(idx, n):
    """(left, right) neighbors on a ring of size ``n`` (traced-value safe)."""
    right = jax.lax.rem(idx + 1, n)
    left = jax.lax.rem(idx + n - 1, n)
    return left, right


def flat_device_id(mesh_axis_names, target_axis, target_idx):
    """Flat logical device id for use as a Pallas remote-DMA ``device_id``.

    Pallas LOGICAL device ids index the mesh's flattened device array. Inside
    a shard_map over a multi-axis mesh, the peer "target_idx along
    target_axis, same coords elsewhere" therefore has flat id
    ``sum_over_axes(coord_i * stride_i)`` with row-major strides.

    Must be called inside shard_map/pallas tracing (uses lax.axis_index).
    """
    sizes = [jax.lax.axis_size(a) for a in mesh_axis_names]
    flat = 0
    for name, size in zip(mesh_axis_names, sizes):
        coord = target_idx if name == target_axis else jax.lax.axis_index(name)
        flat = flat * size + coord
    return flat


def device_coords(mesh: Mesh) -> np.ndarray | None:
    """Physical chip coords per mesh position (TPU only), for ring layout."""
    devs = mesh.devices.ravel()
    if devs[0].platform != "tpu" or getattr(devs[0], "coords", None) is None:
        return None
    return np.array([d.coords for d in devs])


# --------------------------------------------------------------- fleet carve

def carve_replica_meshes(n_replicas: int, devices=None,
                         axis: str = "x", reserve: int = 0):
    """Carve the device pool into ``n_replicas`` equal 1-D meshes, one
    per fleet replica (:mod:`~triton_distributed_tpu.serving.fleet`).

    Deterministic contiguous split: replica ``k`` gets devices
    ``[k*w, (k+1)*w)`` where ``w = len(devices) // n_replicas`` —
    contiguous ranges keep each replica's ICI locality intact on real
    topologies. When the pool is smaller than the fleet (the 1-core CPU
    test harness), replicas share devices round-robin rather than
    refusing: the engines are host-stepped and the interpreter mesh is
    virtual, so sharing is safe there and a loud refusal would make the
    fleet untestable off-TPU.

    ``reserve`` carves ``reserve`` ADDITIONAL equal slices and returns
    ``(active, spares)`` instead of a flat list — the spare-device pool
    the :class:`~triton_distributed_tpu.serving.fleet.FleetAutoscaler`
    spawns grow replicas onto. The split is over ``n_replicas +
    reserve`` ways, so spares are real carved capacity (same width as
    an active replica), not an overcommit.
    """
    import jax

    if n_replicas < 1:
        raise ValueError(f"carve_replica_meshes: n_replicas={n_replicas}")
    if reserve < 0:
        raise ValueError(f"carve_replica_meshes: reserve={reserve}")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    total = n_replicas + reserve
    w = len(devices) // total
    if w == 0:
        meshes = [Mesh(np.array([devices[k % len(devices)]]), (axis,))
                  for k in range(total)]
    else:
        meshes = [Mesh(np.array(devices[k * w:(k + 1) * w]), (axis,))
                  for k in range(total)]
    if reserve == 0:
        return meshes
    return meshes[:n_replicas], meshes[n_replicas:]


# --------------------------------------------------------------- mesh shrink

@dataclass(frozen=True)
class MeshReplan:
    """Result of :func:`replan_mesh`: the surviving mesh plus the fault
    plan that routes collectives around the removed peers.

    * ``mesh`` — the shrunk Mesh (collectives/engines re-built on it see
      only survivors);
    * ``survivors`` — flat indices into the ORIGINAL mesh's raveled
      device array for each surviving position (old-rank bookkeeping:
      workspace slices, KV pages, sharded params indexed by old rank);
    * ``removed_ranks`` / ``removed_slices`` — what the ledger condemned;
    * ``plan`` — a FaultPlan whose ``unhealthy_peers`` carries the
      removed OLD ranks, for code still running on the original mesh
      (``ops.overlap.preflight`` / ``ops.moe`` refuse those paths).
    """

    mesh: Mesh
    survivors: tuple
    removed_ranks: tuple
    removed_slices: tuple
    plan: object


def replan_mesh(mesh: Mesh, ledger, *, dcn_axis: str | None = None,
                base_plan=None) -> MeshReplan:
    """Shrink ``mesh`` to its healthy peers per ``ledger`` (a
    :class:`~triton_distributed_tpu.runtime.health.HealthLedger`) and
    derive the matching fault plan — the ledger's signal aggregation
    turned into an actionable n−1 (or surviving-slice) mesh.

    Two removal granularities, composable:

    * slice-level: ``ledger.unhealthy_slices()`` removes whole rows
      along ``dcn_axis`` (default: the axis literally named "dcn", as
      built by ``multislice.hybrid_mesh``);
    * rank-level: integer peers in ``ledger.unhealthy_peers()`` are flat
      indices into the (slice-pruned) device array. Rank removal keeps
      a mesh reshapeable only in 1-D — for multi-axis meshes a bad rank
      must be covered by its slice's removal, else we raise rather than
      silently deliver a ragged mesh.
    """
    devices = np.asarray(mesh.devices)
    axis_names = tuple(mesh.axis_names)
    flat_ids = np.arange(devices.size).reshape(devices.shape)

    bad_slices = tuple(ledger.unhealthy_slices())
    if bad_slices:
        if dcn_axis is None:
            dcn_axis = "dcn" if "dcn" in axis_names else axis_names[0]
        ax = axis_names.index(dcn_axis)
        keep = [i for i in range(devices.shape[ax]) if i not in bad_slices]
        if not keep:
            raise ValueError(
                f"replan_mesh: every slice along {dcn_axis!r} is "
                f"unhealthy ({bad_slices}) — nothing survives")
        # deleting the KEPT positions leaves exactly the condemned rows
        removed_flat = np.delete(flat_ids, keep, axis=ax).ravel()
        devices = np.take(devices, keep, axis=ax)
        flat_ids = np.take(flat_ids, keep, axis=ax)
    else:
        removed_flat = np.array([], dtype=int)

    bad_ranks = tuple(ledger.unhealthy_peers())
    covered = set(int(r) for r in removed_flat)
    pending = [r for r in bad_ranks if r not in covered]
    if pending:
        if devices.ndim != 1:
            raise ValueError(
                f"replan_mesh: rank-level removal of {pending} needs a "
                f"1-D mesh (got shape {devices.shape}); condemn the "
                f"containing slice instead")
        mask = ~np.isin(flat_ids, pending)
        if not mask.any():
            raise ValueError(
                f"replan_mesh: all ranks unhealthy ({bad_ranks}) — "
                f"nothing survives")
        devices = devices[mask]
        flat_ids = flat_ids[mask]

    new_mesh = Mesh(devices, axis_names)
    plan = ledger.to_fault_plan(base_plan)
    removed = tuple(sorted(set(map(int, removed_flat)) | set(bad_ranks)))
    return MeshReplan(
        mesh=new_mesh,
        survivors=tuple(int(i) for i in flat_ids.ravel()),
        removed_ranks=removed,
        removed_slices=bad_slices,
        plan=plan,
    )
