"""Multi-slice (DCN) mesh construction and scope-aware collectives.

Reference: the CommScope {GPU, INTRA_NODE, INTER_NODE} attribute
(dialect/include/Dialect/Distributed/IR/DistributedAttrDefs.td:45-53)
picks st.gpu / st.sys / nvshmemx per scope, and the kernels split
intra-node (NVLink P2P) from inter-node (RDMA) legs (e.g.
allgather.py:291-375, ep_a2a.py:36-150).

TPU re-design: the scope split is ICI (within a slice — Pallas remote
DMA reaches it) vs DCN (across slices — only XLA collectives ride it,
SURVEY.md §7 hard part d). This module builds hybrid meshes whose axes
are explicitly ICI- or DCN-backed and exposes the predicate the kernel
entries use to auto-select engines: Pallas kernels on ICI axes, XLA
fallbacks on DCN axes (topology.detect_topology → LinkKind.DCN already
routes AllGatherMethod; this is the construction side).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from triton_distributed_tpu.runtime.topology import (
    LinkKind,
    detect_topology,
    slice_id,
)


def num_slices() -> int:
    """Number of ICI-connected slices among the visible devices (1 on a
    single slice or CPU; == process count on typical multi-slice pods)."""
    return max(len({slice_id(d) for d in jax.devices()}), 1)


def create_hybrid_mesh(
    ici_shape, *, dcn_axis: str = "dcn", ici_axes=None,
) -> Mesh:
    """Mesh with a leading DCN axis over slices and ICI axes within.

    ``ici_shape``: per-slice mesh shape (e.g. ``(2, 4)``) — it must
    cover each slice EXACTLY (jax's hybrid-mesh builder groups devices
    by slice and requires a full granule per slice). ``ici_axes`` names
    the axes (default ``("dp", "tp")`` style, last axis "tp"). On a
    single slice the DCN axis has size 1 and any prefix of the devices
    may be used, so the same program runs unchanged — mirroring the
    reference's nnodes==1 specialization (SURVEY.md §4).
    """
    ici_axes = tuple(ici_axes or _default_ici_axes(len(ici_shape)))
    assert len(ici_axes) == len(ici_shape)
    devices = jax.devices()
    n_slices = num_slices()
    per_slice = int(np.prod(ici_shape))
    if n_slices > 1:
        from collections import Counter

        sizes = Counter(slice_id(d) for d in devices)
        bad = {s: c for s, c in sizes.items() if c != per_slice}
        assert not bad, (
            f"ici_shape {ici_shape} (= {per_slice} chips) must cover each "
            f"slice exactly; slice sizes: {dict(sizes)}"
        )
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, (n_slices,) + (1,) * (len(ici_shape) - 1),
            devices=devices,
        ).reshape((n_slices,) + tuple(ici_shape))
    else:
        assert per_slice <= len(devices), (
            f"need {per_slice} devices, have {len(devices)}"
        )
        dev_array = np.asarray(devices[:per_slice]).reshape(
            (1,) + tuple(ici_shape)
        )
    return Mesh(dev_array, (dcn_axis,) + ici_axes)


def _default_ici_axes(n: int):
    named = {1: ("tp",), 2: ("dp", "tp"), 3: ("dp", "pp", "tp")}
    return named.get(n) or tuple(f"ici{i}" for i in range(n))


def is_dcn_axis(mesh: Mesh, axis: str) -> bool:
    """True if collectives along ``axis`` cross slices (DCN) — Pallas
    remote DMA must not be used there; the op entries fall back to XLA
    collectives (≡ the reference's CommScope INTER_NODE dispatch)."""
    return detect_topology(mesh, axis).link_kind == LinkKind.DCN
