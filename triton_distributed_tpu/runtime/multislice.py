"""Multi-slice (DCN) mesh construction and scope-aware collectives.

Reference: the CommScope {GPU, INTRA_NODE, INTER_NODE} attribute
(dialect/include/Dialect/Distributed/IR/DistributedAttrDefs.td:45-53)
picks st.gpu / st.sys / nvshmemx per scope, and the kernels split
intra-node (NVLink P2P) from inter-node (RDMA) legs (e.g.
allgather.py:291-375, ep_a2a.py:36-150).

TPU re-design: the scope split is ICI (within a slice — Pallas remote
DMA reaches it) vs DCN (across slices — only XLA collectives ride it,
SURVEY.md §7 hard part d). This module builds hybrid meshes whose axes
are explicitly ICI- or DCN-backed and exposes the predicate the kernel
entries use to auto-select engines: Pallas kernels on ICI axes, XLA
fallbacks on DCN axes (topology.detect_topology → LinkKind.DCN already
routes AllGatherMethod; this is the construction side).
"""

from __future__ import annotations

import functools as _functools

import jax
import numpy as np
from jax.sharding import Mesh

from triton_distributed_tpu.runtime.topology import (
    LinkKind,
    detect_topology,
    slice_id,
)


def num_slices() -> int:
    """Number of ICI-connected slices among the visible devices (1 on a
    single slice or CPU; == process count on typical multi-slice pods)."""
    return max(len({slice_id(d) for d in jax.devices()}), 1)


def create_hybrid_mesh(
    ici_shape, *, dcn_axis: str = "dcn", ici_axes=None,
) -> Mesh:
    """Mesh with a leading DCN axis over slices and ICI axes within.

    ``ici_shape``: per-slice mesh shape (e.g. ``(2, 4)``) — it must
    cover each slice EXACTLY (jax's hybrid-mesh builder groups devices
    by slice and requires a full granule per slice). ``ici_axes`` names
    the axes (default ``("dp", "tp")`` style, last axis "tp"). On a
    single slice the DCN axis has size 1 and any prefix of the devices
    may be used, so the same program runs unchanged — mirroring the
    reference's nnodes==1 specialization (SURVEY.md §4).
    """
    ici_axes = tuple(ici_axes or _default_ici_axes(len(ici_shape)))
    assert len(ici_axes) == len(ici_shape)
    devices = jax.devices()
    n_slices = num_slices()
    per_slice = int(np.prod(ici_shape))
    if n_slices > 1:
        from collections import Counter

        sizes = Counter(slice_id(d) for d in devices)
        bad = {s: c for s, c in sizes.items() if c != per_slice}
        assert not bad, (
            f"ici_shape {ici_shape} (= {per_slice} chips) must cover each "
            f"slice exactly; slice sizes: {dict(sizes)}"
        )
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_shape, (n_slices,) + (1,) * (len(ici_shape) - 1),
            devices=devices,
        ).reshape((n_slices,) + tuple(ici_shape))
    else:
        assert per_slice <= len(devices), (
            f"need {per_slice} devices, have {len(devices)}"
        )
        dev_array = np.asarray(devices[:per_slice]).reshape(
            (1,) + tuple(ici_shape)
        )
    return Mesh(dev_array, (dcn_axis,) + ici_axes)


def _default_ici_axes(n: int):
    named = {1: ("tp",), 2: ("dp", "tp"), 3: ("dp", "pp", "tp")}
    return named.get(n) or tuple(f"ici{i}" for i in range(n))


def is_dcn_axis(mesh: Mesh, axis: str) -> bool:
    """True if collectives along ``axis`` cross slices (DCN) — Pallas
    remote DMA must not be used there; the op entries fall back to XLA
    collectives (≡ the reference's CommScope INTER_NODE dispatch)."""
    return detect_topology(mesh, axis).link_kind == LinkKind.DCN


# -------------------------------------------------- quantized DCN rails
#
# The hierarchical engines' DCN legs are the slowest transport in the
# system, and until round 8 they moved raw bf16 while the intra-slice
# rings already shipped the compressed wire (ROADMAP PR-3 follow-on).
# These helpers put the lang.wire layout on the rail legs themselves:
# XLA-side quantize/dequant around the ``ppermute`` hops, so they run on
# any backend (DCN has no Pallas reach anyway — Mosaic cast support is
# irrelevant here) and the bytes crossing DCN drop ~2× (payload at
# 1 B/elem + the per-chunk f32 scale plane riding the same hop). The
# XLA-fallback AG compresses DCN the same way (kernels/allgather.py's
# XLA_FALLBACK wire); this is that trick applied to the chunked rails.

def dcn_wire_fetches(a_loc, dcn_axis: str, nd: int, fmt):
    """The quantized twin of the hierarchical AG rail: ``nd - 1``
    independent ``ppermute`` fetches of the OTHER slices' slabs, each
    hop carrying the once-quantized payload + scale plane and each
    arrival dequantized back to the compute dtype. Returns the ``nd``
    chunks in rail order (local slice first, matching the raw rail) —
    chunk ``s`` holds slice ``(my - s)``'s rows. All fetches are issued
    up front, so XLA's async collective machinery still flies the DCN
    legs under whatever consumes chunk 0."""
    import jax

    from triton_distributed_tpu.lang import wire as wirelib

    q, sc = wirelib.quantize_slab(a_loc, fmt)
    chunks = [a_loc]
    for s in range(1, nd):
        perm = [(i, (i + s) % nd) for i in range(nd)]
        qg = jax.lax.ppermute(q, dcn_axis, perm=perm)
        sg = jax.lax.ppermute(sc, dcn_axis, perm=perm)
        chunks.append(wirelib.dequantize_slab(qg, sg, fmt, a_loc.dtype))
    return chunks


def dcn_wire_all_gather(a_loc, dcn_axis: str, fmt):
    """Quantized serial rail: gather the once-quantized payload + scale
    planes across slices and dequantize, with the OWN slab patched back
    exact (it never crossed DCN) — byte-identical to the XLA-fallback
    AG wire in kernels/allgather.py."""
    import jax

    from triton_distributed_tpu.lang import wire as wirelib

    q, sc = wirelib.quantize_slab(a_loc, fmt)
    qg = jax.lax.all_gather(q, dcn_axis, tiled=True)
    sg = jax.lax.all_gather(sc, dcn_axis, tiled=True)
    out = wirelib.dequantize_slab(qg, sg, fmt, a_loc.dtype)
    me = jax.lax.axis_index(dcn_axis)
    return jax.lax.dynamic_update_slice(
        out, a_loc, (me * a_loc.shape[0],) + (0,) * (a_loc.ndim - 1)
    )


def dcn_wire_kv_ship(q_loc, s_loc, dcn_axis: str, *, src: int = 0,
                     dst: int = 1):
    """The KV-page ship's DCN leg (per-device body, inside a shard_map
    over the hybrid mesh): fly the ALREADY-QUANTIZED page payload and
    its per-row f32 scale planes from slice-role ``src`` to ``dst`` as
    PAIRED ``ppermute`` rails — the same paired-rail discipline as the
    other ``dcn_wire_*`` transports, except nothing (re)quantizes here:
    the int8 KV pool's bytes and scales ARE the wire format, so the
    landing is bit-identical to the source pool and the decode slice's
    attention reads exactly what a local prefill would have written.
    Unquantized pools pass ``s_loc=None`` (raw wire, no scale rail).

    Returns ``(q, s)`` whose role-``dst`` shard holds the arrived
    payload (other roles hold the rotated garbage every ppermute
    leaves; callers read only the destination role's shard)."""
    import jax

    perm = [(src, dst)]
    qg = jax.lax.ppermute(q_loc, dcn_axis, perm=perm)
    sg = (
        jax.lax.ppermute(s_loc, dcn_axis, perm=perm)
        if s_loc is not None else None
    )
    return qg, sg


@_functools.lru_cache(maxsize=32)
def kv_ship_rail(mesh, dcn_axis: str, has_scales: bool, src: int = 0,
                 dst: int = 1):
    """Jitted role-stacked wrapper of :func:`dcn_wire_kv_ship`: takes
    arrays whose LEADING dim indexes the slice role (sharded over
    ``dcn_axis``; the source role's slab is the payload, the rest is
    don't-care) and returns the same layout with role ``dst`` holding
    the arrivals. Built per (mesh, rails) and cached — jax's jit cache
    handles the per-payload-shape retraces."""
    import jax
    from jax.sharding import PartitionSpec as P

    if has_scales:
        def body(q, s):
            return dcn_wire_kv_ship(q, s, dcn_axis, src=src, dst=dst)

        fn = jax.shard_map(
            body, mesh=mesh, in_specs=(P(dcn_axis), P(dcn_axis)),
            out_specs=(P(dcn_axis), P(dcn_axis)), check_vma=False,
        )
    else:
        def body(q):
            qg, _ = dcn_wire_kv_ship(q, None, dcn_axis, src=src, dst=dst)
            return (qg,)

        fn = jax.shard_map(
            body, mesh=mesh, in_specs=(P(dcn_axis),),
            out_specs=(P(dcn_axis),), check_vma=False,
        )
    return jax.jit(fn)


def dcn_wire_reduce_scatter(part, dcn_axis: str, nd: int, fmt):
    """Quantized twin of the hierarchical RS leg's ``psum_scatter``: a
    manual ``ppermute`` reduce ring whose hops carry per-hop-quantized
    partials (payload + scale rails) with the f32 dequant-accumulate
    fold — the RS wire contract (one bounded rounding per hop), the
    same bytes the fused gemm_rs wire ring ships, now on the DCN rail.
    ``part``: (rows, cols) partial with rows divisible by ``nd``;
    returns this slice's (rows/nd, cols) reduced stripe."""
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.lang import wire as wirelib

    me = jax.lax.axis_index(dcn_axis)
    m_s = part.shape[0] // nd
    perm = [(i, (i - 1) % nd) for i in range(nd)]

    def stripe(i):
        return jax.lax.dynamic_slice(
            part, (i * m_s, 0), (m_s, part.shape[1])
        )

    def step(h, acc):
        q, sc = wirelib.quantize_slab(acc, fmt)
        q = jax.lax.ppermute(q, dcn_axis, perm=perm)
        sc = jax.lax.ppermute(sc, dcn_axis, perm=perm)
        arrived = wirelib.dequantize_slab(q, sc, fmt, jnp.float32)
        nxt = jax.lax.rem(me + 2 + h, nd)
        return (arrived + stripe(nxt).astype(jnp.float32)).astype(
            part.dtype
        )

    acc = stripe(jax.lax.rem(me + 1, nd))
    return jax.lax.fori_loop(0, nd - 1, step, acc)


# ------------------------------------------------ trip-summary exchange

def exchange_trip_summaries(summary, *, max_bytes: int = 4096):
    """All-gather per-slice watchdog :class:`TripSummary` objects over
    the DCN *host* channel, so every slice can run the same
    ``watchdog.merge_trip_summaries`` and agree on which slice wedged.

    The exchange is a fixed-width uint8 row per process (length-prefixed
    JSON, padded to ``max_bytes``) through
    ``multihost_utils.process_allgather`` — a host collective, usable
    exactly when the device fabric may be wedged is NOT guaranteed, but
    the coordinator-backed host channel usually survives a device hang.
    Single-process (CPU sim / one slice): the identity, ``[summary]``.
    """
    from triton_distributed_tpu.runtime.watchdog import TripSummary

    if jax.process_count() <= 1:
        return [summary]

    from jax.experimental import multihost_utils

    blob = summary.to_json().encode()
    if len(blob) + 4 > max_bytes:
        raise ValueError(
            f"trip summary ({len(blob)}B) exceeds max_bytes={max_bytes}")
    row = np.zeros(max_bytes, dtype=np.uint8)
    row[:4] = np.frombuffer(
        np.uint32(len(blob)).tobytes(), dtype=np.uint8)
    row[4:4 + len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(row))
    gathered = gathered.reshape(-1, max_bytes)
    out = []
    for r in gathered:
        ln = int(np.frombuffer(r[:4].tobytes(), dtype=np.uint32)[0])
        out.append(TripSummary.from_json(r[4:4 + ln].tobytes().decode()))
    return out
