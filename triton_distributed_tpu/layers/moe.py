"""MoE layers: the EP AllToAll layer and the two full MoE MLP flavours.

Reference: python/triton_dist/layers/nvidia/ep_a2a_layer.py —
``EPAll2AllLayer`` (:40-240): preprocess (splits/cumsum/indices) →
dispatch → (expert compute by caller) → combine, owning the symmetric
buffers. The full MLP compositions correspond to
test_ep_moe_inference.py and the ag_group_gemm/moe_reduce_rs pipelines.

TPU re-design: buffers belong to XLA, so the layer state is just the
context; ``EPAll2AllLayer`` keeps the reference's dispatch/combine
split so callers can run custom expert code between the legs, while
``EPMoEMLP`` / ``MoETPMLP`` are the one-call layers models use.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.ops.moe import EPMoEContext, ep_moe, ep_moe_device
from triton_distributed_tpu.ops.moe_tp import (
    MoETPContext,
    ag_group_gemm,
    align_routing,
    moe_reduce_rs,
    moe_tp_mlp,
)


@dataclass(frozen=True)
class EPAll2AllLayer:
    """Dispatch/combine pair around caller-provided expert compute
    (≡ EPAll2AllLayer, ep_a2a_layer.py:40-240). Device-level: call the
    methods inside a shard_map over ``ctx.mesh``."""

    ctx: ma.MoEAllToAllContext

    def dispatch(self, tokens_sorted, splits):
        """(M, H) expert-sorted tokens + (E,) splits → ((n, max_m, H)
        received tokens, (n, epr) received splits)."""
        from triton_distributed_tpu.kernels.all_to_all import all_to_all_device

        packed = ma.pack_slots(
            self.ctx, *ma.dispatch_stage(self.ctx, tokens_sorted, splits)
        )
        recv = all_to_all_device(
            packed, self.ctx.n, self.ctx.axis, self.ctx.mesh.axis_names,
            collective_id=self.ctx.collective_id,
        )
        return ma.recv_tokens_view(self.ctx, recv)

    def combine(self, toks, splits, m_total: int):
        """(n, max_m, H) processed tokens → (m_total, H) back in this
        rank's original sorted order."""
        from triton_distributed_tpu.kernels.all_to_all import all_to_all_device

        comb = all_to_all_device(
            ma.combine_stage(self.ctx, toks),
            self.ctx.n, self.ctx.axis, self.ctx.mesh.axis_names,
            collective_id=self.ctx.collective_id,
        )
        return ma.combine_unstage(
            self.ctx, ma.combine_unpack(self.ctx, comb), splits, m_total
        )


@dataclass(frozen=True)
class EPMoEMLP:
    """Expert-parallel MoE MLP layer (router + dispatch + grouped MLP +
    combine in one call). Params: {"router": (H, E), "up": (E, H, F),
    "down": (E, F, H)} — expert dims sharded over ``ctx.axis``."""

    ctx: EPMoEContext

    def init(self, key, ffn_dim: int, dtype=None):
        dtype = dtype or self.ctx.dtype
        h, e = self.ctx.hidden, self.ctx.num_experts
        k1, k2, k3 = jax.random.split(key, 3)
        s = 1.0 / (h ** 0.5)
        return {
            "router": jax.random.normal(k1, (h, e), jnp.float32) * s,
            "up": jax.random.normal(k2, (e, h, ffn_dim), dtype) * s,
            "down": jax.random.normal(k3, (e, ffn_dim, h), dtype)
            * (1.0 / (ffn_dim ** 0.5)),
        }

    def __call__(self, params, x):
        """x: (M, H) token-sharded over ``ctx.axis``. Returns (M, H)."""
        logits = x.astype(jnp.float32) @ params["router"]
        return ep_moe(x, logits, params["up"], params["down"], self.ctx)

    def device_body(self, params, x):
        """Per-device body for composition inside a model's shard_map."""
        logits = x.astype(jnp.float32) @ params["router"]
        return ep_moe_device(x, logits, params["up"], params["down"], self.ctx)


@dataclass(frozen=True)
class MoETPMLP:
    """Tensor-parallel MoE MLP layer. Weights: up (E, H, F) F-sharded,
    down (E, F, H) F-sharded over ``ctx.axis``.

    ``fused=True`` (default): the single-body moe_tp_mlp op — one sort,
    both grouped GEMMs, psum_scatter; differentiable, DP-aware via
    ``ctx.batch_axes``. ``fused=False``: the composed ag_group_gemm →
    act → moe_reduce_rs pipeline over the Pallas ring reduce-scatter
    (inference; routing threaded once, ≡ the reference's two-kernel
    orchestration, moe_reduce_rs.py:882-1020)."""

    ctx: MoETPContext
    activation: str = "silu"
    fused: bool = True

    def __call__(self, params, x, topk_ids, topk_weights):
        """x: (M, H) token-sharded; topk_ids/topk_weights: (M, k)
        routing (row-sharded like x, or replicated — the entry
        reshards). Returns (M, H) token-sharded."""
        if self.fused:
            return moe_tp_mlp(
                x, topk_ids, topk_weights, params["up"], params["down"],
                self.ctx, activation=self.activation,
            )
        routing = align_routing(self.ctx, topk_ids)
        y = ag_group_gemm(x, routing, params["up"], self.ctx)
        act = jax.nn.silu if self.activation == "silu" else jax.nn.gelu
        return moe_reduce_rs(act(y), routing, topk_weights, params["down"], self.ctx)
