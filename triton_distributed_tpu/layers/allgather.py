"""AllGather layer exposing every engine variant with shared bookkeeping.

Reference: python/triton_dist/layers/nvidia/low_latency_allgather_layer
.py — ``AllGatherLayer`` (:31-195) exposing 8 fast-AG variants
(pull / push-2d / push-3d / LL × scopes) with per-call signal-target
bookkeeping.

TPU re-design: the signal bookkeeping is the DMA semaphore's job, so
the layer reduces to method selection + jit caches: RING_1D (torus
neighbor ring), RING_BIDIR (both directions, halves latency), LL_SMALL
(single-shot full-mesh push for latency-bound sizes — the LL-protocol
analogue), XLA_FALLBACK (lax.all_gather). ``auto`` picks by topology
and message size like AllGatherMethod selection (allgather.py:44-69).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax

from triton_distributed_tpu.kernels.allgather import all_gather
from triton_distributed_tpu.runtime import AllGatherMethod


@dataclass(frozen=True)
class AllGatherLayer:
    """≡ AllGatherLayer (low_latency_allgather_layer.py:31-195)."""

    mesh: jax.sharding.Mesh
    axis: str = "x"
    collective_id: int = 2

    def __call__(self, x, method: AllGatherMethod | None = None):
        """x: (M, ...) rows sharded over ``axis`` → gathered (M, ...)
        replicated rows on every rank."""
        return all_gather(
            x, self.mesh, self.axis,
            method=method, collective_id=self.collective_id,
        )

    # Named variants, mirroring the reference's forward_* family
    def forward_ring(self, x):
        return self(x, AllGatherMethod.RING_1D)

    def forward_ring_bidir(self, x):
        return self(x, AllGatherMethod.RING_BIDIR)

    def forward_ll(self, x):
        """Low-latency small-message path (≡ the LL-protocol variants,
        low_latency_allgather.py:532-624)."""
        return self(x, AllGatherMethod.LL_SMALL)

    def forward_ll_persist(self, x):
        """Barrier-free LL over the persistent double-buffered
        workspace (≡ the reference's no-barrier LL protocol,
        low_latency_allgather.py:532-569): the entry barrier the
        stateless path pays IS the latency at small sizes. Eager calls
        only (the workspace is layer-owned state)."""
        return self(x, AllGatherMethod.LL_PERSIST)

    def forward_xla(self, x):
        return self(x, AllGatherMethod.XLA_FALLBACK)
