"""Layer package (L5): composable modules over the kernel/op layers.

≡ python/triton_dist/layers/nvidia/ — SpGQAFlashDecodeAttention
(sp_flash_decode_layer.py:43), EPAll2AllLayer (ep_a2a_layer.py:40),
AllGatherLayer (low_latency_allgather_layer.py:31) — plus the
tensor-parallel linear/MLP layers that make the overlap ops composable
into transformer blocks (beyond the reference's inference-only scope).
"""

from triton_distributed_tpu.layers.allgather import AllGatherLayer
from triton_distributed_tpu.layers.attention import (
    RaggedPagedAttention,
    SpGQAFlashDecodeAttention,
    append_kv,
    paged_append_kv,
)
from triton_distributed_tpu.layers.linear import (
    ColumnParallelLinear,
    ParallelMLP,
    RowParallelLinear,
)
from triton_distributed_tpu.layers.moe import EPAll2AllLayer, EPMoEMLP, MoETPMLP

__all__ = [
    "AllGatherLayer",
    "RaggedPagedAttention",
    "SpGQAFlashDecodeAttention",
    "append_kv",
    "paged_append_kv",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "ParallelMLP",
    "EPAll2AllLayer",
    "EPMoEMLP",
    "MoETPMLP",
]
