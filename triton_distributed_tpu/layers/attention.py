"""Sequence-parallel flash-decode attention layer.

Reference: python/triton_dist/layers/nvidia/sp_flash_decode_layer.py —
``SpGQAFlashDecodeAttention(nn.Module)`` (:45-184): local split-kv
attention on the rank's KV shard → low-latency AG of per-rank partial
(out, lse) → inter-rank combine, with symmetric AG buffers grown on
demand (:60-77).

TPU re-design: the layer is a thin stateless callable over the
flash-decode kernels (kernels/flash_decode.py) — no buffer management
is needed because XLA owns allocation; the only state worth keeping is
the geometry + jit caches, which the kernel module already holds.
Exposes both the host entry (global arrays on a mesh) and the device
body (for composition inside a model's shard_map).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from triton_distributed_tpu.kernels.flash_decode import (
    quantize_kv,
    sp_paged_gqa_fwd_batch_decode,
    sp_paged_gqa_fwd_batch_decode_q8,
    sp_gqa_fwd_batch_decode,
    sp_gqa_fwd_batch_decode_device,
    sp_gqa_fwd_batch_decode_q8,
)


@dataclass(frozen=True)
class SpGQAFlashDecodeAttention:
    """SP/CP decode attention: KV cache sequence-sharded over ``axis``.

    q_heads/kv_heads/head_dim describe the GQA geometry; ``scale`` defaults
    to 1/sqrt(head_dim); ``soft_cap`` > 0 enables logit soft-capping
    (≡ the ctor args at sp_flash_decode_layer.py:45-59).
    """

    mesh: jax.sharding.Mesh
    axis: str = "x"
    q_heads: int = 32
    kv_heads: int = 8
    head_dim: int = 128
    # dp mesh axes the BATCH dim is sharded over (the dp×tp serving
    # layout: batch over dp, sequence over ``axis``); () = replicated.
    # Non-paged modes only — the paged pool layout is rank-major.
    batch_axes: tuple = ()
    scale: float | None = None
    soft_cap: float = 0.0
    # None → auto (kernel heuristic: shard_len/2 clamped to [1024, 4096])
    block_k: int | None = None
    use_pallas: bool = True
    # "bhsd" (B, Hkv, S, D) is the fast decode layout: each KV block is
    # one contiguous DMA run (97% of HBM SOL measured on v5e vs 87% for
    # the reference-style "bshd" strided view). "bshd" kept for callers
    # holding (B, S, Hkv, D) caches.
    kv_layout: str = "bhsd"
    # For serialized-artifact (AOT) deployment of the local decode, use
    # kernels.flash_decode.gqa_fwd_batch_decode_aot directly (≡ the
    # reference's USE_TRITON_DISTRIBUTED_AOT path picking *_aot entries,
    # sp_flash_decode_layer.py:32-39); this layer always dispatches the
    # jit-cached SP pipeline.

    def __call__(self, q, k_cache, v_cache, global_kv_lens,
                 block_table=None):
        """q: (B, Hq, D) replicated; k/v_cache: (B, S, Hkv, D) [bshd] or
        (B, Hkv, S, D) [bhsd] with S sharded over ``axis``;
        global_kv_lens: (B,) total lengths. Returns (B, Hq, D) replicated
        (≡ forward, sp_flash_decode_layer.py:78-184).

        PAGED mode (``block_table`` given, ≡ the reference layer's
        block_table arg + page_size ctor knob): k/v_cache are page POOLS
        (R·npages_local, Hkv, page, D) sharded over ``axis`` and
        block_table is (R, B, pages_per_slice) of local page ids.

        INT8 mode: pass each cache as a ``{"q": int8 (B, Hkv, S, D),
        "scale": f32 (B, Hkv, S)}`` dict (the same quantized-leaf
        convention as the expert weights; build with
        :func:`quantize_kv` / models' ``kv_quant`` config) — half the
        KV bytes at rest and on the attention DMA stream."""
        if block_table is not None:
            if isinstance(k_cache, dict):       # int8 page pools
                return sp_paged_gqa_fwd_batch_decode_q8(
                    q, k_cache["q"], k_cache["scale"],
                    v_cache["q"], v_cache["scale"], global_kv_lens,
                    block_table, self.mesh, self.axis,
                    scale=self.scale, soft_cap=self.soft_cap,
                )
            return sp_paged_gqa_fwd_batch_decode(
                q, k_cache, v_cache, global_kv_lens, block_table,
                self.mesh, self.axis, scale=self.scale,
                soft_cap=self.soft_cap, use_pallas=self.use_pallas,
            )
        return self._nonpaged(q, k_cache, v_cache, global_kv_lens, False)

    def _nonpaged(self, q, k_cache, v_cache, global_kv_lens, with_lse):
        """The ONE non-paged dispatch (dict → int8, array → bf16)."""
        if isinstance(k_cache, dict):
            return sp_gqa_fwd_batch_decode_q8(
                q, k_cache["q"], k_cache["scale"],
                v_cache["q"], v_cache["scale"], global_kv_lens,
                self.mesh, self.axis, scale=self.scale,
                soft_cap=self.soft_cap, block_k=self.block_k,
                with_lse=with_lse, batch_axes=self.batch_axes,
            )
        return sp_gqa_fwd_batch_decode(
            q, k_cache, v_cache, global_kv_lens, self.mesh, self.axis,
            scale=self.scale, soft_cap=self.soft_cap,
            block_k=self.block_k, use_pallas=self.use_pallas,
            kv_layout=self.kv_layout, with_lse=with_lse,
            batch_axes=self.batch_axes,
        )

    def partials(self, q, k_cache, v_cache, global_kv_lens,
                 block_table=None):
        """Like ``__call__`` but returning the merged ``(out, lse)``
        pair — the softmax merge is associative, so the caller can fold
        FURTHER partials (e.g. the decode step's just-produced token as
        an exact single-position partial via ``combine_partials``)
        without the cache append feeding the attention kernel. With
        ``block_table``, the caches are page POOLS (the paged serving
        mode; see ``__call__``)."""
        if block_table is not None:
            if isinstance(k_cache, dict):
                return sp_paged_gqa_fwd_batch_decode_q8(
                    q, k_cache["q"], k_cache["scale"],
                    v_cache["q"], v_cache["scale"], global_kv_lens,
                    block_table, self.mesh, self.axis,
                    scale=self.scale, soft_cap=self.soft_cap,
                    with_lse=True,
                )
            return sp_paged_gqa_fwd_batch_decode(
                q, k_cache, v_cache, global_kv_lens, block_table,
                self.mesh, self.axis, scale=self.scale,
                soft_cap=self.soft_cap, use_pallas=self.use_pallas,
                with_lse=True,
            )
        return self._nonpaged(q, k_cache, v_cache, global_kv_lens, True)

    def token_partial(self, q, k_new, v_new):
        """The (out, lse) partial of ONE just-produced KV position, in
        THIS layer's score convention (scale + soft_cap) so it can be
        merged with :meth:`partials` results without domain drift: a
        weight-1 softmax over a single position has out = v and
        lse = its (soft-capped, scaled) raw score.

        q: (B, Hq, D); k_new/v_new: (B, Hkv, D). Returns
        ((B, Hq, D) f32, (B, Hq) f32)."""
        b, hq, d = q.shape
        hkv = k_new.shape[1]
        g = hq // hkv
        scale = self.scale if self.scale is not None else 1.0 / (d ** 0.5)
        qg = q.reshape(b, hkv, g, d)
        s = jnp.einsum(
            "bhgd,bhd->bhg",
            qg.astype(jnp.float32), k_new.astype(jnp.float32),
        ) * scale
        if self.soft_cap > 0.0:
            s = self.soft_cap * jnp.tanh(s / self.soft_cap)
        out = jnp.broadcast_to(
            v_new[:, :, None].astype(jnp.float32), (b, hkv, g, d)
        ).reshape(b, hq, d)
        return out, s.reshape(b, hq)

    def device_body(self, q, k_shard, v_shard, global_kv_lens):
        """Per-device body for composition inside a model's shard_map."""
        return sp_gqa_fwd_batch_decode_device(
            q, k_shard, v_shard, global_kv_lens, self.axis,
            scale=self.scale, soft_cap=self.soft_cap,
            block_k=self.block_k, use_pallas=self.use_pallas,
            kv_layout=self.kv_layout,
        )


@dataclass(frozen=True)
class RaggedPagedAttention:
    """Serving-layout ragged paged attention: pools sharded over the
    KV-HEAD dim on ``axis`` (GQA heads are independent — no cross-rank
    LSE merge, unlike the sequence-sharded decode layer above), q/out
    in the head-major GQA-rows packing, metadata replicated. The layer
    the continuous-batching serving step composes
    (models/transformer.serving_step); see
    kernels/ragged_paged_attention.py for the kernel contract and
    docs/SERVING.md for the state layout."""

    mesh: jax.sharding.Mesh
    axis: str = "x"
    group: int = 4                 # G = Hq // Hkv
    scale: float | None = None
    soft_cap: float = 0.0
    use_pallas: bool = True

    def __call__(self, qp, k_pool, v_pool, kv_lens, q_lens, q_starts,
                 block_table, *, topologies=None, block_q: int = 8,
                 n_bufs: int = 2, with_lse: bool = False):
        """qp: (Hkv, T·G, D) packed rows sharded P(axis) on dim 0;
        k_pool/v_pool: (npages, Hkv, page, D) arrays or int8
        ``{"q","scale"}`` dicts, sharded P(None, axis); metadata —
        including the optional (R, 2+2W) per-row attention-topology
        descriptors — replicated. Returns (Hkv, T·G, D) sharded like
        qp — or the ``((Hkv, T·G, D), (Hkv, T·G))`` partial pair under
        ``with_lse`` (the cp-decode path merges per-shard partials with
        ``flash_decode.combine_gqa_partials``; head sharding makes the
        LSE per-rank-local, so the pair shards exactly like qp)."""
        from jax.sharding import PartitionSpec as P

        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            ragged_paged_attention,
            ragged_paged_attention_xla,
        )

        quant = isinstance(k_pool, dict)
        g, block = self.group, block_q
        use_pallas = self.use_pallas
        has_topo = topologies is not None

        def local(qp, table, kv_lens, q_lens, q_starts, *rest):
            if has_topo:
                topo, *pools = rest
            else:
                topo, pools = None, rest
            fn = (ragged_paged_attention if use_pallas
                  else ragged_paged_attention_xla)
            kw = dict(group=g, scale=self.scale, soft_cap=self.soft_cap,
                      topologies=topo)
            if use_pallas:
                kw["block_q"] = block
                kw["n_bufs"] = n_bufs
            if quant:
                kq, ks, vq, vs = pools
                out, lse = fn(qp, kq, vq, kv_lens, q_lens, q_starts,
                              table, k_scale=ks, v_scale=vs, **kw)
            else:
                kc, vc = pools
                out, lse = fn(qp, kc, vc, kv_lens, q_lens, q_starts,
                              table, **kw)
            return (out, lse) if with_lse else out

        pools = (
            (k_pool["q"], k_pool["scale"], v_pool["q"], v_pool["scale"])
            if quant else (k_pool, v_pool)
        )
        meta = (P(),) if has_topo else ()
        sharded = jax.shard_map(
            local,
            mesh=self.mesh,
            in_specs=(P(self.axis), P(), P(), P(), P()) + meta
            + tuple(P(None, self.axis) for _ in pools),
            out_specs=(
                (P(self.axis), P(self.axis)) if with_lse else P(self.axis)
            ),
            check_vma=False,
        )
        extra = (topologies,) if has_topo else ()
        return sharded(qp, block_table, kv_lens, q_lens, q_starts,
                       *extra, *pools)


def append_kv(k_cache, v_cache, kv_lens, k_new, v_new, kv_layout="bhsd",
              k_quant=None, v_quant=None):
    """Append one decode step's K/V at each batch row's current length.

    k_cache/v_cache: (B, Hkv, S, D) [``kv_layout="bhsd"``, native
    default] or (B, S, Hkv, D) [``"bshd"``]; k_new/v_new: (B, Hkv, D);
    kv_lens: (B,)
    lengths BEFORE the append. Returns updated caches and lengths.
    (The reference leaves cache management to the serving stack; provided
    here so the models package can run real decode loops.)

    A row whose length has reached the cache capacity S drops the write
    (JAX out-of-bounds scatter semantics) while the returned length
    still increments — callers must enforce capacity up front (see the
    check in models.Transformer.generate).

    INT8 caches (``{"q", "scale"}`` dicts, bhsd only): the new rows are
    quantized per (b, h) — one f32 scale per appended D-row — and both
    planes are scattered. ``k_quant``/``v_quant``: optional already-
    computed ``(int8 values, f32 scales)`` pairs (from
    :func:`~triton_distributed_tpu.kernels.flash_decode.quantize_kv`);
    passing them makes the cached token BIT-IDENTICAL to whatever the
    caller attended — re-quantizing a dequantized bf16 round-trip can
    shift ints by 1 LSB (ADVICE r5).
    """
    if isinstance(k_cache, dict):
        assert kv_layout == "bhsd", "int8 caches are bhsd-native"
        kq_new, ks_new = k_quant if k_quant is not None else quantize_kv(k_new)
        vq_new, vs_new = v_quant if v_quant is not None else quantize_kv(v_new)
        b = k_cache["q"].shape[0]
        heads = jnp.arange(k_cache["q"].shape[1])
        bi = jnp.arange(b)[:, None]
        hi = heads[None, :]
        li = kv_lens[:, None]
        k_cache = {
            "q": k_cache["q"].at[bi, hi, li].set(kq_new),
            "scale": k_cache["scale"].at[bi, hi, li].set(ks_new),
        }
        v_cache = {
            "q": v_cache["q"].at[bi, hi, li].set(vq_new),
            "scale": v_cache["scale"].at[bi, hi, li].set(vs_new),
        }
        return k_cache, v_cache, kv_lens + 1
    b = k_cache.shape[0]
    rows = jnp.arange(b)
    if kv_layout == "bshd":
        k_cache = k_cache.at[rows, kv_lens].set(k_new.astype(k_cache.dtype))
        v_cache = v_cache.at[rows, kv_lens].set(v_new.astype(v_cache.dtype))
    else:
        heads = jnp.arange(k_cache.shape[1])
        bi = rows[:, None]
        hi = heads[None, :]
        li = kv_lens[:, None]
        k_cache = k_cache.at[bi, hi, li].set(
            k_new.astype(k_cache.dtype)
        )
        v_cache = v_cache.at[bi, hi, li].set(
            v_new.astype(v_cache.dtype)
        )
    return k_cache, v_cache, kv_lens + 1


def paged_append_kv(k_pool, v_pool, block_table, kv_lens, k_new, v_new,
                    k_quant=None, v_quant=None):
    """Append one decode step's K/V into PAGE POOLS at each row's
    current length — the paged twin of :func:`append_kv` (≡ the
    reference kernels writing through the block table,
    flash_decode.py:763-846).

    k_pool/v_pool: (R·npages_local, Hkv, page, D) pools — or int8
    ``{"q", "scale"}`` dicts with (R·npages_local, Hkv, page) scale
    pools; block_table: (R, B, pages_per_slice) LOCAL page ids (rank
    r's pool shard is rows [r·npages_local, (r+1)·npages_local));
    kv_lens: (B,) GLOBAL lengths before the append. A row at global
    position L lives on sequence slice L // (pages_per_slice·page), in
    local page (L mod s_loc) // page, at offset L mod page. Rows at
    capacity drop the write (JAX OOB scatter semantics), like
    append_kv. Written at the global level — GSPMD partitions the
    scatter (on one device this is a plain in-place write; a rank-local
    shard_map twin is the multi-host optimization, same as the
    reference's per-rank table writes)."""
    r, b, pps = block_table.shape
    pool0 = k_pool["q"] if isinstance(k_pool, dict) else k_pool
    npages_local = pool0.shape[0] // r
    page = pool0.shape[2]
    s_loc = pps * page
    rows = jnp.arange(b)
    slice_idx = kv_lens // s_loc
    local = kv_lens % s_loc
    off = local % page
    local_id = block_table[
        jnp.clip(slice_idx, 0, r - 1), rows, local // page
    ]
    # rows past capacity get an out-of-range pool index on purpose —
    # the scatter drops them (same contract as append_kv)
    pool_idx = jnp.where(
        kv_lens < r * s_loc,
        slice_idx * npages_local + local_id,
        pool0.shape[0],
    )
    heads = jnp.arange(pool0.shape[1])
    pi = pool_idx[:, None]
    hi = heads[None, :]
    oi = off[:, None]
    if isinstance(k_pool, dict):
        # pre-quantized pairs keep the cache bit-identical to what the
        # caller attended (see append_kv)
        kq_new, ks_new = k_quant if k_quant is not None else quantize_kv(k_new)
        vq_new, vs_new = v_quant if v_quant is not None else quantize_kv(v_new)
        k_pool = {
            "q": k_pool["q"].at[pi, hi, oi].set(kq_new),
            "scale": k_pool["scale"].at[pi, hi, oi].set(ks_new),
        }
        v_pool = {
            "q": v_pool["q"].at[pi, hi, oi].set(vq_new),
            "scale": v_pool["scale"].at[pi, hi, oi].set(vs_new),
        }
        return k_pool, v_pool, kv_lens + 1
    k_pool = k_pool.at[pi, hi, oi].set(k_new.astype(k_pool.dtype))
    v_pool = v_pool.at[pi, hi, oi].set(v_new.astype(v_pool.dtype))
    return k_pool, v_pool, kv_lens + 1
