"""Tensor-parallel linear layers over the differentiable overlap ops.

The reference stops at raw kernels + thin modules; these are the
Megatron-style column/row-parallel linears that make the overlap ops
(ops/overlap.py: ag_gemm / gemm_rs) composable into transformer blocks,
in the sequence-parallel layout (activations row-sharded between
blocks). Column then row = one AG-GEMM and one GEMM-RS per MLP, the
flagship overlap pattern of the reference (tutorials 07/08).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from triton_distributed_tpu.ops.overlap import OverlapContext, ag_gemm, gemm_rs


@dataclass(frozen=True)
class ColumnParallelLinear:
    """y = AG(x) @ W, W col-sharded: (K, N/tp) per rank.

    Input (M, K) row-sharded (sequence-parallel); output (M, N) with N
    sharded — feeds a RowParallelLinear.
    """

    ctx: OverlapContext

    def init(self, key, in_dim: int, out_dim: int, dtype=jnp.bfloat16):
        s = 1.0 / (in_dim ** 0.5)
        return {"w": jax.random.normal(key, (in_dim, out_dim), dtype) * s}

    def __call__(self, params, x):
        return ag_gemm(x, params["w"], self.ctx)


@dataclass(frozen=True)
class RowParallelLinear:
    """y = RS(x @ W), W row-sharded: (K/tp, N) per rank.

    Input (M, K) with K sharded; output (M, N) row-sharded — the
    sequence-parallel residual layout.
    """

    ctx: OverlapContext

    def init(self, key, in_dim: int, out_dim: int, dtype=jnp.bfloat16):
        s = 1.0 / (in_dim ** 0.5)
        return {"w": jax.random.normal(key, (in_dim, out_dim), dtype) * s}

    def __call__(self, params, x):
        return gemm_rs(x, params["w"], self.ctx)


@dataclass(frozen=True)
class ParallelMLP:
    """Column → activation → Row: the canonical TP MLP (one AG-GEMM and
    one GEMM-RS per call — reference tutorials 07+08 fused pattern)."""

    up: ColumnParallelLinear
    down: RowParallelLinear
    activation: str = "gelu"

    def init(self, key, hidden: int, ffn: int, dtype=jnp.bfloat16):
        k1, k2 = jax.random.split(key)
        return {
            "up": self.up.init(k1, hidden, ffn, dtype),
            "down": self.down.init(k2, ffn, hidden, dtype),
        }

    def __call__(self, params, x):
        h = self.up(params["up"], x)
        act = jax.nn.silu if self.activation == "silu" else jax.nn.gelu
        return self.down(params["down"], act(h))
