"""Sharding-aware checkpoint save/restore + step management.

The reference has no checkpointing ("kernel library, not a trainer",
SURVEY.md §5) — but this framework ships a trainer, so checkpoint /
resume is part of completeness. Format: one .npz of flattened leaves +
a JSON manifest of the tree structure (dependable across versions —
no serialization-API drift), with the framed artifact store
(tools/native.py) providing the checksummed IO. Restore places each
leaf onto the sharding of a matching "like" pytree, so a checkpoint
written on one mesh restores onto another (the resharding is a
device_put).
"""

from __future__ import annotations

import io
import json
import pathlib
import re

import jax
import numpy as np

from triton_distributed_tpu.tools.native import artifact_read, artifact_write

_STEP_RE = re.compile(r"^step_(\d+)$")


def _flatten(pytree):
    leaves, treedef = jax.tree_util.tree_flatten(pytree)
    return leaves, treedef


def save_checkpoint(path, pytree) -> None:
    """Write ``pytree`` (arrays at the leaves) to ``path``.

    Multi-host: call from every process; only process 0 writes (leaves
    are fully-addressable host copies via device_get).
    """
    leaves, treedef = _flatten(pytree)
    arrays = []
    for l in leaves:
        if isinstance(l, jax.Array) and not l.is_fully_addressable:
            # multi-host sharded leaf: assemble the global value on every
            # process (device_get would raise on non-addressable shards)
            from jax.experimental import multihost_utils

            arrays.append(np.asarray(multihost_utils.process_allgather(
                l, tiled=True)))
        else:
            arrays.append(np.asarray(jax.device_get(l)))
    if jax.process_index() != 0:
        return
    buf = io.BytesIO()
    np.savez(buf, *arrays)
    manifest = json.dumps({"treedef": str(treedef), "n": len(arrays)})
    blob = manifest.encode() + b"\x00" + buf.getvalue()
    pathlib.Path(path).parent.mkdir(parents=True, exist_ok=True)
    artifact_write(str(path), blob)


def restore_checkpoint(path, like):
    """Restore onto the structure AND shardings of ``like``.

    ``like`` supplies the tree structure, dtypes, and target shardings
    (its leaves may be jax.Arrays or ShapeDtypeStructs + shardings via
    ``.sharding``); each stored leaf is device_put onto the matching
    target sharding.
    """
    blob = artifact_read(str(path))
    sep = blob.index(b"\x00")
    manifest = json.loads(blob[:sep].decode())
    data = np.load(io.BytesIO(blob[sep + 1 :]))
    arrays = [data[k] for k in data.files]
    leaves, treedef = _flatten(like)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"checkpoint has {len(arrays)} leaves, target has {len(leaves)}"
        )
    if manifest["treedef"] != str(treedef):
        # same leaf count but different structure/key order — restoring
        # would silently assign leaves to the wrong parameters
        raise ValueError(
            "checkpoint tree structure does not match target:\n"
            f"  stored: {manifest['treedef']}\n  target: {treedef}"
        )
    out = []
    for arr, tgt in zip(arrays, leaves):
        if tuple(arr.shape) != tuple(tgt.shape):
            raise ValueError(
                f"leaf shape mismatch: stored {arr.shape} vs target {tgt.shape}"
            )
        arr = arr.astype(tgt.dtype)
        sharding = getattr(tgt, "sharding", None)
        out.append(jax.device_put(arr, sharding) if sharding is not None
                   else jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


class CheckpointManager:
    """Step-numbered checkpoints with retention (``step_N`` files in a
    directory; the trainer-loop counterpart of orbax's manager, kept
    dependency-light)."""

    def __init__(self, directory, *, keep: int = 3):
        self.dir = pathlib.Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    def _steps(self):
        steps = []
        for p in self.dir.iterdir():
            m = _STEP_RE.match(p.name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def save(self, step: int, pytree) -> None:
        save_checkpoint(self.dir / f"step_{step}", pytree)
        if jax.process_index() == 0:
            for old in self._steps()[: -self.keep]:
                (self.dir / f"step_{old}").unlink(missing_ok=True)

    def latest_step(self):
        steps = self._steps()
        return steps[-1] if steps else None

    def restore(self, like, step: int | None = None):
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        return restore_checkpoint(self.dir / f"step_{step}", like)
