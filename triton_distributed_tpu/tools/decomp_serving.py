"""Decompose the serving decode step's attention+projections side.

VERDICT r4: the MoE block got a decomposition-driven 2.8× (2.60 → 0.92
ms, docs/PERF.md); the attention+rest side (1.58 ms of the 2.50 ms
step) had not. This tool times each component of the non-MoE side of
``Transformer.decode_step`` at the serving headline config with the
bench.py fori-loop methodology, printing one JSON line per component —
the measured table lives in docs/PERF.md and drives which pieces get
attacked.

Run on the chip::

    python -m triton_distributed_tpu.tools.decomp_serving

Components (the decode_step data path, models/transformer.py):
embed gather → rmsnorm → wqkv (W8A8) → flash-decode q8 partials →
token partial + combine → append_kv (int8 scatter) → wo (W8A8) →
rmsnorm → [MoE block, timed elsewhere] → final rmsnorm → lm_head
(W8A16) → argmax.
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def main() -> None:
    sys.path.insert(0, ".")
    from bench import bench_loop, perturb

    from triton_distributed_tpu.kernels.flash_decode import (
        combine_partials,
        quantize_kv,
    )
    from triton_distributed_tpu.layers import append_kv
    from triton_distributed_tpu.models import Transformer, TransformerConfig

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("x",))
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        b, s_cap = 128, 2048
        cfg = TransformerConfig(
            vocab=4096, n_layers=1, hidden=7168, ffn=2048, n_heads=56,
            n_kv_heads=8, head_dim=128, moe="ep", moe_layers=(0,),
            num_experts=8, topk=8, param_dtype=jnp.bfloat16,
            moe_weight_quant="int8", moe_act_quant="int8",
            kv_quant="int8", dense_weight_quant="int8",
            dense_act_quant="int8",
        )
        lo, hi = 16, 128
    else:
        b, s_cap = 8, 256
        cfg = TransformerConfig(
            vocab=512, n_layers=1, hidden=256, ffn=128, n_heads=8,
            n_kv_heads=4, head_dim=32, moe="ep", moe_layers=(0,),
            num_experts=8, topk=2, param_dtype=jnp.bfloat16,
        )
        lo, hi = 1, 3
    model = Transformer(cfg, mesh, tp_axis="x")
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(7)), model.shardings(),
    )
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)
    blk = params["blocks"][0]
    c = cfg

    lens = jnp.asarray(
        np.random.default_rng(11).integers(s_cap // 8, 3 * s_cap // 4, (b,)),
        jnp.int32,
    )
    caches = model.init_cache(b, s_cap)
    ck, cv = caches[0]
    key = jax.random.PRNGKey(8)
    x0 = jax.random.normal(key, (b, c.hidden), c.dtype)
    q0 = jax.random.normal(key, (b, c.n_heads, c.head_dim), c.dtype)
    k0 = jax.random.normal(key, (b, c.n_kv_heads, c.head_dim), c.dtype)
    logits0 = jax.random.normal(key, (b, c.vocab), jnp.float32)

    def report(name, t_us, note=""):
        print(
            json.dumps({"component": name, "us": round(t_us, 1), "note": note}),
            flush=True,
        )

    def run(name, step, state, note=""):
        try:
            t = bench_loop(step, state, lo=lo, hi=hi)
            report(name, t * 1e6, note)
            return t
        except Exception as e:  # keep the table coming
            print(
                json.dumps({"component": name,
                            "error": f"{type(e).__name__}: {e}"[:200]}),
                flush=True,
            )
            return float("nan")

    # ---- full step (the headline) + MoE block, for the residual
    moe_state = model.init_decode_state(b)
    toks0 = jnp.zeros((b,), jnp.int32)

    def full_step(state, s):
        prm, caches, lens_, toks, mst = state
        if mst is None:
            logits, caches, lens_ = model.decode_step(prm, caches, lens_, toks)
        else:
            logits, caches, lens_, mst = model.decode_step(
                prm, caches, lens_, toks, mst
            )
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        s = s + jnp.sum(toks.astype(jnp.float32))
        return (prm, caches, lens_, toks, mst), s

    t_full = run(
        "full_step", full_step,
        (params, model.init_cache(b, s_cap), lens, toks0, moe_state),
    )

    from triton_distributed_tpu.ops import create_ep_moe_state, ep_moe

    ctx = model._moe_ep_ctx(-(-b // model.token_shards), inference=True)
    mst2 = create_ep_moe_state(ctx) if ctx.transport == "fused" else None
    w_up, w_down = (
        w if isinstance(w, dict) else w.astype(c.dtype)
        for w in (blk["moe_up"], blk["moe_down"])
    )

    def moe_step(state, s):
        x, mst = state
        logits_r = x.astype(jnp.float32) @ blk["router"]
        if mst is None:
            y = ep_moe(x, logits_r, w_up, w_down, ctx)
        else:
            y, mst = ep_moe(x, logits_r, w_up, w_down, ctx, state=mst)
        s = s + jnp.sum(y.astype(jnp.float32))
        return (perturb(x, s), mst), s

    t_moe = run("moe_block", moe_step, (x0, mst2))
    if np.isfinite(t_full) and np.isfinite(t_moe):
        report("attn_rest(residual)", (t_full - t_moe) * 1e6)

    # ---- the attention kernel (SP q8 partials at the mixed lens)
    def attn_step(state, s):
        q, = state
        o, lse = model._sp_attn.partials(q, ck, cv, lens)
        s = s + jnp.sum(o.astype(jnp.float32))
        return (perturb(q, s),), s

    run("flash_decode_q8", attn_step, (q0,),
        note=f"mixed lens U[{s_cap//8},{3*s_cap//4}]")

    # ---- token partial + combine
    def tok_step(state, s):
        q, k = state
        o_c = jnp.zeros((b, c.n_heads, c.head_dim), jnp.float32)
        lse_c = jnp.zeros((b, c.n_heads), jnp.float32)
        o_new, lse_new = model._sp_attn.token_partial(q, k, k)
        o, _ = combine_partials(
            jnp.stack([o_c, o_new]), jnp.stack([lse_c, lse_new]),
            out_dtype=jnp.float32,
        )
        s = s + jnp.sum(o)
        return (perturb(q, s), k), s

    run("token_partial+combine", tok_step, (q0, k0))

    # ---- append_kv (int8 quantize + scatter at one position per row)
    def append_step(state, s):
        ck_, cv_, lens_, k = state
        ck_, cv_, lens_ = append_kv(ck_, cv_, lens_ % (s_cap - 1), k, k)
        s = s + jnp.sum(lens_.astype(jnp.float32))
        return (ck_, cv_, lens_, perturb(k, s)), s

    run("append_kv", append_step, (ck, cv, lens, k0))

    # ---- dense projections (storage-dispatching _dmm)
    def proj(name, w, m_in, note=""):
        x = jax.random.normal(key, (b, m_in), c.dtype)

        def step(state, s):
            x, = state
            y = model._dmm(x, w)
            s = s + jnp.sum(y.astype(jnp.float32))
            return (perturb(x, s),), s

        run(name, step, (x,), note)

    proj("wqkv", blk["wqkv"], c.hidden, "W8A8" if c.dense_act_quant else "")
    proj("wo", blk["wo"], c.q_dim, "W8A8" if c.dense_act_quant else "")

    def head_step(state, s):
        x, = state
        y = model._dmm(x, params["lm_head"], out_dtype=jnp.float32,
                       act_quant=False) if isinstance(params["lm_head"], dict) \
            else x.astype(jnp.float32) @ params["lm_head"]
        s = s + jnp.sum(y)
        return (perturb(x, s),), s

    run("lm_head", head_step, (x0,), "W8A16")

    # ---- glue: rmsnorms, argmax, embed gather, router
    def norm_step(state, s):
        x, = state
        y = model._rmsnorm(x, blk["norm_attn"])
        s = s + jnp.sum(y.astype(jnp.float32))
        return (perturb(x, s),), s

    run("rmsnorm(x1)", norm_step, (x0,))

    def argmax_step(state, s):
        lg, = state
        t = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        s = s + jnp.sum(t.astype(jnp.float32))
        return (perturb(lg, s),), s

    run("argmax", argmax_step, (logits0,))

    def embed_step(state, s):
        t, = state
        x = params["embed"][t].astype(c.dtype)
        s = s + jnp.sum(x.astype(jnp.float32))
        t = (t + 1) % c.vocab
        return (t,), s

    run("embed_gather", embed_step, (toks0,))


if __name__ == "__main__":
    main()
