"""Native runtime: ctypes bindings + XLA-native degradation targets.

Two kinds of "native" live here:

* ctypes bindings for the native host library (csrc/tdtpu_native.cpp).
  Reference: csrc/{op_pybind.cc,registry.cc} expose CUDA host utilities
  into Python via pybind11/torch; here the binding layer is ctypes over a
  plain C ABI (pybind11 is not in this toolchain) and the library is
  built on first use with g++ (cached under csrc/build/). Every entry
  point has a pure-python fallback so the package works where no
  compiler exists — the native path is the fast path, not a hard
  dependency.
* **XLA-native collective equivalents** (bottom of the module): the
  degradation targets of ``ops.overlap.with_fallback`` — pure
  ``lax.all_gather``/``psum_scatter`` + ``jnp.dot`` twins of the fused
  Pallas engines, one per engine in the degradation matrix
  (docs/ROBUSTNESS.md). Numerically equivalent (same f32 accumulation),
  strictly slower (no compute/communication overlap), and dependent on
  nothing but XLA — the floor the serving stack can always stand on.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import struct
import subprocess
import threading

import numpy as np

_ART_MAGIC = 0x5452415550544454          # "TDTPUART" little-endian
_FNV_OFF, _FNV_PRIME = 1469598103934665603, 1099511628211


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFF
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h

_ROOT = pathlib.Path(__file__).resolve().parents[2]
_SRC = _ROOT / "csrc" / "tdtpu_native.cpp"
_SO = _ROOT / "csrc" / "build" / "libtdtpu_native.so"
_lock = threading.Lock()
_lib_cache: list = []          # [lib or None] once resolved


def _build() -> bool:
    _SO.parent.mkdir(parents=True, exist_ok=True)
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-o", str(_SO), str(_SRC)]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def native_lib():
    """The loaded library, or None (build failed / disabled)."""
    with _lock:
        if _lib_cache:
            return _lib_cache[0]
        lib = None
        if os.environ.get("TDTPU_NO_NATIVE") != "1":
            fresh = _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime
            if fresh or _build():
                try:
                    lib = ctypes.CDLL(str(_SO))
                    u8p = ctypes.POINTER(ctypes.c_uint8)
                    lib.tdtpu_artifact_write.argtypes = [
                        ctypes.c_char_p, u8p, ctypes.c_uint64]
                    lib.tdtpu_artifact_size.restype = ctypes.c_int64
                    lib.tdtpu_artifact_size.argtypes = [ctypes.c_char_p]
                    lib.tdtpu_artifact_read.argtypes = [
                        ctypes.c_char_p, u8p, ctypes.c_uint64]
                    lib.tdtpu_moe_align_block_size.restype = ctypes.c_int64
                    lib.tdtpu_dataset_open.restype = ctypes.c_void_p
                    lib.tdtpu_dataset_len.restype = ctypes.c_uint64
                    lib.tdtpu_dataset_close.argtypes = [ctypes.c_void_p]
                    lib.tdtpu_dataset_len.argtypes = [ctypes.c_void_p]
                except OSError:
                    lib = None
        _lib_cache.append(lib)
        return lib


# ------------------------------------------------------------------ artifact

def artifact_write(path: str, blob: bytes) -> None:
    """Atomic checksummed write. Both paths emit the SAME on-disk format
    (magic | len | payload | fnv1a) so artifacts stay readable across
    hosts with and without the native library."""
    lib = native_lib()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        rc = lib.tdtpu_artifact_write(path.encode(), buf, len(blob))
        if rc == 0:
            return
    framed = (
        struct.pack("<QQ", _ART_MAGIC, len(blob)) + blob
        + struct.pack("<Q", _fnv1a(blob))
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(framed)
    os.replace(tmp, path)


def artifact_read(path: str) -> bytes:
    lib = native_lib()
    if lib is not None:
        size = lib.tdtpu_artifact_size(path.encode())
        if size >= 0:
            out = (ctypes.c_uint8 * size)()
            rc = lib.tdtpu_artifact_read(path.encode(), out, size)
            if rc == -3:
                raise IOError(f"artifact checksum mismatch: {path}")
            if rc == 0:
                return bytes(out)
    raw = pathlib.Path(path).read_bytes()
    if len(raw) >= 8 and struct.unpack_from("<Q", raw, 0)[0] == _ART_MAGIC:
        # Magic present → this IS a framed artifact; a bad length or
        # checksum is corruption/truncation, not a legacy file (returning
        # the raw bytes would hand garbage to a downstream parser —
        # mirror the native rc=-3 error path instead; ADVICE r1).
        if len(raw) < 24:
            raise IOError(f"artifact truncated: {path}")
        _, length = struct.unpack_from("<QQ", raw, 0)
        if len(raw) != 24 + length:
            raise IOError(
                f"artifact length mismatch: {path} ({len(raw)} bytes, "
                f"frame says {24 + length})"
            )
        payload = raw[16 : 16 + length]
        (stored,) = struct.unpack_from("<Q", raw, 16 + length)
        if _fnv1a(payload) != stored:
            raise IOError(f"artifact checksum mismatch: {path}")
        return payload
    return raw                     # pre-framing legacy file: raw payload


# ----------------------------------------------------------------- moe align

def moe_align_block_size_host(topk_ids, num_experts: int, block_m: int):
    """Host (numpy) twin of kernels/moe_utils.moe_align_block_size —
    native-accelerated token sort/pad for CPU-side preprocessing
    (≡ moe_ag_scatter_align_block_size, csrc/lib/moe_utils.cu:61-356).
    Returns (sorted_token_ids, block_expert, splits) numpy arrays."""
    ids = np.ascontiguousarray(topk_ids, dtype=np.int32)
    if ids.size and (ids.min() < 0 or ids.max() >= num_experts):
        raise ValueError(
            f"expert ids out of range [0, {num_experts}): "
            f"[{ids.min()}, {ids.max()}]"
        )
    m, k = ids.shape
    total = m * k
    cap = int(np.ceil((total + num_experts * (block_m - 1)) / block_m)) * block_m
    lib = native_lib()
    if lib is not None:
        sti = np.empty((cap,), np.int32)
        be = np.empty((cap // block_m,), np.int32)
        splits = np.empty((num_experts,), np.int32)
        rc = lib.tdtpu_moe_align_block_size(
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(m), ctypes.c_int64(k),
            ctypes.c_int64(num_experts), ctypes.c_int64(block_m),
            sti.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            be.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            splits.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int64(cap),
        )
        if rc < 0:
            raise RuntimeError(
                f"tdtpu_moe_align_block_size failed (rc={rc})"
            )
        return sti, be, splits
    # numpy fallback — same layout contract
    flat = ids.reshape(-1)
    splits = np.bincount(flat, minlength=num_experts).astype(np.int32)
    padded = (splits + block_m - 1) // block_m * block_m
    padded_offs = np.concatenate([[0], np.cumsum(padded)[:-1]]).astype(np.int64)
    offs = np.concatenate([[0], np.cumsum(splits)[:-1]]).astype(np.int64)
    order = np.argsort(flat, kind="stable").astype(np.int32)
    se = flat[order]
    dest = padded_offs[se] + (np.arange(total) - offs[se])
    sti = np.full((cap,), total, np.int32)
    sti[dest] = order
    starts = np.arange(cap // block_m) * block_m
    be = np.searchsorted(np.cumsum(padded), starts, side="right").astype(np.int32)
    be = np.clip(be, 0, num_experts - 1)
    return sti, be, splits


# -------------------------------------------------------------- token dataset

class TokenDataset:
    """mmap'd uint32 token file with seeded random-window sampling — the
    native IO path of the training loop. ``sample`` returns
    (batch, seqlen+1) uint32: inputs = [:, :-1], targets = [:, 1:]."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lib = native_lib()
        self._handle = None
        if self._lib is not None:
            self._handle = self._lib.tdtpu_dataset_open(self.path.encode())
        if self._handle is None:
            self._mm = np.memmap(self.path, dtype=np.uint32, mode="r")

    def __len__(self):
        if self._handle is not None:
            return int(self._lib.tdtpu_dataset_len(self._handle))
        return int(self._mm.shape[0])

    def sample(self, batch: int, seqlen: int, seed: int):
        out = np.empty((batch, seqlen + 1), np.uint32)
        if self._handle is not None:
            rc = self._lib.tdtpu_dataset_sample(
                ctypes.c_void_p(self._handle), ctypes.c_uint64(seed),
                ctypes.c_int64(batch), ctypes.c_int64(seqlen),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            )
            if rc == 0:
                return out
            raise ValueError(f"dataset shorter than seqlen+1={seqlen + 1}")
        n = len(self)
        if n < seqlen + 1:
            raise ValueError(f"dataset shorter than seqlen+1={seqlen + 1}")
        rng = np.random.default_rng(seed)
        offs = rng.integers(0, n - seqlen, size=batch)
        for b, off in enumerate(offs):
            out[b] = self._mm[off : off + seqlen + 1]
        return out

    def close(self):
        if self._handle is not None:
            self._lib.tdtpu_dataset_close(ctypes.c_void_p(self._handle))
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------- XLA-native degradation targets
# The fused-engine fallbacks used by ops.overlap.with_fallback and the
# EP-MoE transport demotion. Deliberately the *simplest correct* XLA
# programs (gather → dot, dot → psum_scatter): when a preflight probe
# has already failed, predictability beats cleverness.
#
# Instrumented like the Pallas engines (lang.maybe_instrument): an XLA
# collective can wedge too — a dead host mid-rendezvous hangs
# all_gather/psum_scatter exactly like a lost DMA credit — and the
# degradation path being the UNINSTRUMENTED one would mean the watchdog
# goes blind at the moment it is most needed (ROADMAP: "watchdog
# coverage for the XLA collective paths"). The builders key on
# config.interp_key() so arming a watchdog / activating a plan rebuilds
# with the heartbeat hooks traced in, same contract as the kernels.

import functools as _functools


@_functools.lru_cache(maxsize=128)
def _xla_ag_gemm_fn(mesh, axis, batch_axes, out_dtype, ikey=None,
                    wire=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu import lang
    from triton_distributed_tpu.lang import wire as wirelib

    ba = tuple(batch_axes)
    mx = wire == "int8-mxu"

    def body(a_loc, b_loc):
        fmt = (
            wirelib.make_wire_format(
                wirelib.wire_payload(wire), a_loc.shape[0], strict=False
            )
            if wire is not None else None
        )
        if fmt is None:
            a_full = jax.lax.all_gather(a_loc, axis, tiled=True)
            return jnp.dot(
                a_full, b_loc, preferred_element_type=jnp.float32
            ).astype(out_dtype)
        # byte-identical lang.wire rails over the XLA gather: the
        # degradation target preserves the wire layout (and for
        # int8-mxu the epilogue-fold numerics) so accuracy tests run on
        # any backend
        q, sc = wirelib.quantize_slab(a_loc, fmt)
        qg = jax.lax.all_gather(q, axis, tiled=True)
        sg = jax.lax.all_gather(sc, axis, tiled=True)
        if mx:
            bq, bs = wirelib.quantize_cols(b_loc)
            row_scale = jnp.repeat(sg[:, :1], fmt.chunk_rows, axis=0)
            acc = jax.lax.dot_general(
                qg, bq, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            return (
                acc.astype(jnp.float32) * row_scale * bs
            ).astype(out_dtype)
        a_full = wirelib.dequantize_slab(qg, sg, fmt, a_loc.dtype)
        me = jax.lax.axis_index(axis)
        a_full = jax.lax.dynamic_update_slice(
            a_full, a_loc, (me * a_loc.shape[0], 0)
        )
        return jnp.dot(
            a_full, b_loc, preferred_element_type=jnp.float32
        ).astype(out_dtype)

    body = lang.maybe_instrument(
        body, axis=axis, site="ag_gemm", collective_id="xla_fallback",
        n=mesh.shape[axis],
    )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ba + (axis,) if ba else axis, None), P(None, axis)),
        out_specs=P(ba if ba else None, axis),
        check_vma=False,
    )
    return jax.jit(fn)


def xla_ag_gemm(a, b, mesh, axis, *, batch_axes=(), out_dtype=None,
                wire_dtype=None):
    """AllGather(A) @ B via plain XLA — the ag_gemm degradation target.
    Same layout contract as ``kernels.ag_gemm`` (rows sharded over
    ``(*batch_axes, axis)``, B cols sharded over ``axis``).
    ``wire_dtype`` ('fp8'/'int8'/'int8-mxu'): the degraded path keeps
    shipping the byte-identical lang.wire payload+scale rails — and for
    'int8-mxu' the epilogue-fold numerics — so a demotion never changes
    the wire format mid-flight."""
    import jax.numpy as jnp

    from triton_distributed_tpu.config import interp_key
    from triton_distributed_tpu.lang import wire as wirelib

    out_dtype = jnp.dtype(out_dtype or a.dtype)
    return _xla_ag_gemm_fn(
        mesh, axis, tuple(batch_axes), out_dtype, interp_key(),
        wirelib.normalize_wire(wire_dtype),
    )(a, b)


@_functools.lru_cache(maxsize=128)
def _xla_gemm_rs_fn(mesh, axis, batch_axes, out_dtype, ikey=None,
                    wire=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu import lang
    from triton_distributed_tpu.lang import wire as wirelib

    ba = tuple(batch_axes)
    n = mesh.shape[axis]

    def body(a_loc, b_loc):
        part = jnp.dot(a_loc, b_loc, preferred_element_type=jnp.float32)
        fmt = (
            wirelib.make_wire_format(
                wirelib.wire_payload(wire), part.shape[0] // n,
                strict=False,
            )
            if wire is not None and part.shape[0] % n == 0 else None
        )
        if fmt is not None:
            # quantized ppermute reduce ring — the same per-hop
            # payload+scale rails and f32 dequant-accumulate as the
            # Pallas wire ring (runtime.multislice shares the body with
            # the hierarchical DCN legs)
            from triton_distributed_tpu.runtime.multislice import (
                dcn_wire_reduce_scatter,
            )

            return dcn_wire_reduce_scatter(
                part.astype(out_dtype), axis, n, fmt
            )
        return jax.lax.psum_scatter(
            part, axis, scatter_dimension=0, tiled=True
        ).astype(out_dtype)

    body = lang.maybe_instrument(
        body, axis=axis, site="gemm_rs", collective_id="xla_fallback",
        n=n,
    )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ba if ba else None, axis), P(axis, None)),
        out_specs=P(ba + (axis,) if ba else axis, None),
        check_vma=False,
    )
    return jax.jit(fn)


def xla_gemm_rs(a, b, mesh, axis, *, batch_axes=(), out_dtype=None,
                wire_dtype=None):
    """(A @ B) → ReduceScatter via plain XLA — the gemm_rs degradation
    target. Same layout contract as ``kernels.gemm_rs``. ``wire_dtype``
    keeps the demoted path on the byte-identical quantized reduce ring
    (per-hop payload+scale rails, f32 dequant-accumulate)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.config import interp_key
    from triton_distributed_tpu.lang import wire as wirelib

    out_dtype = jnp.dtype(out_dtype or a.dtype)
    return _xla_gemm_rs_fn(
        mesh, axis, tuple(batch_axes), out_dtype, interp_key(),
        wirelib.normalize_wire(wire_dtype),
    )(a, b)


def xla_kv_ship(payload, shardings):
    """KV-page transfer via plain XLA data movement — the kv_ship
    degradation target: a ``device_put`` of the (already wire-shaped)
    payload pytree onto the decode mesh's placements. No collective, no
    rails, nothing to deadlock — XLA/the runtime route the bytes over
    whatever link connects the meshes (DCN across slices, ICI within
    one), which is exactly the predictability a degraded path wants.
    The payload stays in its quantized pool form (int8 pages + f32
    per-row scale planes), so even the fallback never widens the wire
    — a demotion changes the transport, never the bytes.

    Heartbeated like every other transport: the ``device_put`` is a
    cross-mesh transfer that can wedge exactly like a collective (a
    peer slice going away mid-flight hangs the runtime's copy), so the
    body runs under the host-mode ``kv_ship`` watchdog instrument —
    this was the LAST unheartbeated fallback entry point (``xla_ag_gemm``
    and ``xla_gemm_rs`` instrument inside their shard_map bodies)."""
    import jax

    from triton_distributed_tpu import lang

    def body():
        return jax.tree.map(
            lambda x, s: x if s is None else jax.device_put(x, s),
            payload, shardings,
            is_leaf=lambda x: x is None,
        )

    return lang.maybe_instrument(
        body, axis=None, site="kv_ship", collective_id="xla_fallback",
        n=1,
    )()
