"""AOT precompilation CLI (≡ tools/compile_aot.py + scripts/
gen_aot_code.sh: the reference drives its AOT generator over the kernel
list in scripts/aot_kernels.txt — the flash-decode family — producing a
dispatcher library; deployment then runs with USE_TRITON_DISTRIBUTED_AOT).

Here the same workflow is::

    python -m triton_distributed_tpu.tools.compile_aot \
        --kernel gqa_decode --cache-dir .aot_cache \
        --batch 4 --q-heads 32 --kv-heads 8 --head-dim 128 \
        --seq 4096 --seq 8192 --dtype bfloat16

which serializes one artifact per sequence-length point; serving code
loads them via ``kernels.flash_decode.gqa_fwd_batch_decode_aot`` with
the same hyperparameters and never retraces.
"""

from __future__ import annotations

import argparse


def _decode_space(args):
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(args.dtype)
    pts = []
    for s in args.seq:
        q = jax.ShapeDtypeStruct((args.batch, args.q_heads, args.head_dim), dtype)
        kv = jax.ShapeDtypeStruct(
            (args.batch, args.kv_heads, s, args.head_dim)
            if args.kv_layout == "bhsd"
            else (args.batch, s, args.kv_heads, args.head_dim),
            dtype,
        )
        lens = jax.ShapeDtypeStruct((args.batch,), jnp.int32)
        pts.append((q, kv, kv, lens))
    return pts


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--kernel", choices=["gqa_decode"], default="gqa_decode")
    p.add_argument("--cache-dir", default=".aot_cache")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--q-heads", type=int, default=32)
    p.add_argument("--kv-heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=128)
    p.add_argument("--seq", type=int, action="append", default=None,
                   help="KV capacity point; repeatable (default: 4096 8192)")
    p.add_argument("--block-k", type=int, default=2048)
    p.add_argument("--kv-layout", choices=["bhsd", "bshd"], default="bhsd")
    p.add_argument("--soft-cap", type=float, default=0.0)
    p.add_argument("--scale", type=float, default=None,
                   help="attention scale; None = 1/sqrt(head_dim) "
                        "(part of the artifact identity — must match the "
                        "serving library's value)")
    p.add_argument("--dtype", default="bfloat16")
    args = p.parse_args(argv)
    if args.seq is None:
        args.seq = [4096, 8192]

    from triton_distributed_tpu.kernels.flash_decode import (
        gqa_fwd_batch_decode_aot,
    )

    lib = gqa_fwd_batch_decode_aot(
        scale=args.scale, block_k=args.block_k, soft_cap=args.soft_cap,
        kv_layout=args.kv_layout, cache_dir=args.cache_dir,
    )
    for pt in _decode_space(args):
        path = lib.compile(*pt)
        print(f"compiled {args.kernel} {[tuple(a.shape) for a in pt]} -> {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
