"""Tools package (L6): profiling, AOT compilation, native runtime utilities.

≡ the reference's tools/ (compile.py, compile_aot.py, runtime/
triton_aot_runtime.cc) and utils.group_profile (utils.py:417-502).
"""

from triton_distributed_tpu.tools.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from triton_distributed_tpu.tools.aot import (
    AotLibrary,
    aot_compile,
    aot_compile_spaces,
    aot_load,
)
from triton_distributed_tpu.tools.native import (
    TokenDataset,
    artifact_read,
    artifact_write,
    moe_align_block_size_host,
    native_lib,
)
from triton_distributed_tpu.tools.profile import (
    group_profile,
    gather_traces,
    merge_chrome_traces,
)

__all__ = [
    "aot_compile",
    "aot_load",
    "aot_compile_spaces",
    "AotLibrary",
    "group_profile",
    "gather_traces",
    "merge_chrome_traces",
    "native_lib",
    "artifact_write",
    "artifact_read",
    "moe_align_block_size_host",
    "TokenDataset",
    "save_checkpoint",
    "restore_checkpoint",
    "CheckpointManager",
]
