"""Profiling tools: per-process traces gathered into one timeline.

Reference: utils.group_profile (python/triton_dist/utils.py:417-502) —
torch.profiler per rank → chrome traces → gather to rank0 over
torch.distributed → pid/tid remap by rank*1e8 → one merged, gzipped
timeline (merge machinery :282-414).

TPU re-design: ``group_profile`` wraps ``jax.profiler.trace`` writing
one subdir per process (the profiler is already whole-device — every
TPU op lands in the trace, no per-kernel hooks needed), and
``merge_chrome_traces`` performs the same pid-offset merge over any
chrome-format ``*.trace.json(.gz)`` the runs produced. On multi-host
deployments each host writes to the shared log dir when one exists;
pods WITHOUT shared storage run ``gather_traces`` first — an IN-BAND
gather of every host's trace files to process 0 (≡ the reference's
torch.distributed gather, utils.py:417-502). ``merge_chrome_traces``
refuses (loudly) to produce a partial merge when it can see that other
processes' traces are missing.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import pathlib

import jax

PID_STRIDE = 10**8   # ≡ the reference's rank*1e8 remap (utils.py:330)


@contextlib.contextmanager
def group_profile(log_dir=".profiles", *, enabled: bool = True,
                  create_perfetto_trace: bool = False):
    """Trace the enclosed block on every process (≡ group_profile,
    utils.py:417). Writes ``<log_dir>/process-<i>/``."""
    if not enabled:
        yield None
        return
    path = pathlib.Path(log_dir) / f"process-{jax.process_index()}"
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(
        str(path), create_perfetto_trace=create_perfetto_trace
    ):
        yield path


def _load_trace(fname):
    op = gzip.open if fname.endswith(".gz") else open
    with op(fname, "rt") as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def gather_traces(log_dir=".profiles"):
    """IN-BAND gather of every process's trace directory to process 0
    (≡ the reference gathering per-rank chrome traces to rank 0 over
    torch.distributed, utils.py:417-502) — for multi-host runs WITHOUT
    a shared log dir. Every process tars its ``process-<i>`` subdir and
    the blobs ride ``multihost_utils.process_allgather`` (padded to the
    max size — trace volume, not a hot path); process 0 unpacks all of
    them under its ``log_dir`` so :func:`merge_chrome_traces` sees the
    full set. Single-process: no-op. Returns ``log_dir``."""
    if jax.process_count() == 1:
        return pathlib.Path(log_dir)
    import io
    import tarfile

    import numpy as np
    from jax.experimental import multihost_utils

    log_dir = pathlib.Path(log_dir)
    mine = log_dir / f"process-{jax.process_index()}"
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        if mine.is_dir():
            tar.add(mine, arcname=mine.name)
    blob = np.frombuffer(buf.getvalue(), np.uint8)
    sizes = np.asarray(
        multihost_utils.process_allgather(np.array([blob.size], np.int64))
    ).reshape(-1)
    cap = int(sizes.max())
    padded = np.zeros((cap,), np.uint8)
    padded[: blob.size] = blob
    blobs = np.asarray(multihost_utils.process_allgather(padded))
    if jax.process_index() == 0:
        for i, (b, s) in enumerate(zip(blobs, sizes)):
            if i == jax.process_index() or s == 0:
                continue
            with tarfile.open(
                fileobj=io.BytesIO(b[: int(s)].tobytes()), mode="r:gz"
            ) as tar:
                tar.extractall(log_dir, filter="data")
    return log_dir


def merge_chrome_traces(log_dir=".profiles", out="merged_trace.json.gz"):
    """Merge every chrome trace under ``log_dir`` into one timeline,
    remapping pids by process index (≡ utils.py:282-414). Returns the
    output path, or None if no traces were found.

    On a multi-process run the merge REFUSES to cover only the local
    host's traces: if fewer process dirs are present than
    ``jax.process_count()``, it raises and names the fix (shared log
    dir, or :func:`gather_traces` first) instead of silently producing
    a partial timeline that reads as complete."""
    log_dir = pathlib.Path(log_dir)
    merged = []
    found = False
    procs_seen = set()
    for proc_dir in sorted(log_dir.glob("process-*")):
        try:
            idx = int(proc_dir.name.split("-")[1])
        except (IndexError, ValueError):
            continue
        pats = ("**/*.trace.json.gz", "**/*.trace.json", "**/trace.json.gz")
        files = sorted({f for p in pats for f in glob.glob(
            str(proc_dir / p), recursive=True)})
        for fname in files:
            found = True
            procs_seen.add(idx)
            for ev in _load_trace(fname):
                ev = dict(ev)
                if "pid" in ev:
                    try:
                        ev["pid"] = int(ev["pid"]) + idx * PID_STRIDE
                    except (TypeError, ValueError):
                        pass
                merged.append(ev)
    if not found:
        return None
    if jax.process_count() > 1 and len(procs_seen) < jax.process_count():
        raise RuntimeError(
            f"merge_chrome_traces: traces found for processes "
            f"{sorted(procs_seen)} but this run has "
            f"{jax.process_count()} — no shared log dir? Run "
            "tools.gather_traces(log_dir) before merging (in-band "
            "gather to process 0), or point every host at shared "
            "storage. Refusing to write a partial merge that would "
            "read as complete."
        )
    out_path = log_dir / out
    with gzip.open(out_path, "wt") as f:
        json.dump({"traceEvents": merged}, f)
    return out_path
