"""Profiling tools: per-process traces gathered into one timeline.

Reference: utils.group_profile (python/triton_dist/utils.py:417-502) —
torch.profiler per rank → chrome traces → gather to rank0 over
torch.distributed → pid/tid remap by rank*1e8 → one merged, gzipped
timeline (merge machinery :282-414).

TPU re-design: ``group_profile`` wraps ``jax.profiler.trace`` writing
one subdir per process (the profiler is already whole-device — every
TPU op lands in the trace, no per-kernel hooks needed), and
``merge_chrome_traces`` performs the same pid-offset merge over any
chrome-format ``*.trace.json(.gz)`` the runs produced. On multi-host
deployments each host writes to the shared log dir; the merge runs
wherever the files are visible (no in-band gather needed — TPU pods
mount shared storage, unlike the reference's NCCL gather).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import pathlib

import jax

PID_STRIDE = 10**8   # ≡ the reference's rank*1e8 remap (utils.py:330)


@contextlib.contextmanager
def group_profile(log_dir=".profiles", *, enabled: bool = True,
                  create_perfetto_trace: bool = False):
    """Trace the enclosed block on every process (≡ group_profile,
    utils.py:417). Writes ``<log_dir>/process-<i>/``."""
    if not enabled:
        yield None
        return
    path = pathlib.Path(log_dir) / f"process-{jax.process_index()}"
    path.mkdir(parents=True, exist_ok=True)
    with jax.profiler.trace(
        str(path), create_perfetto_trace=create_perfetto_trace
    ):
        yield path


def _load_trace(fname):
    op = gzip.open if fname.endswith(".gz") else open
    with op(fname, "rt") as f:
        data = json.load(f)
    return data["traceEvents"] if isinstance(data, dict) else data


def merge_chrome_traces(log_dir=".profiles", out="merged_trace.json.gz"):
    """Merge every chrome trace under ``log_dir`` into one timeline,
    remapping pids by process index (≡ utils.py:282-414). Returns the
    output path, or None if no traces were found."""
    log_dir = pathlib.Path(log_dir)
    merged = []
    found = False
    for proc_dir in sorted(log_dir.glob("process-*")):
        try:
            idx = int(proc_dir.name.split("-")[1])
        except (IndexError, ValueError):
            continue
        pats = ("**/*.trace.json.gz", "**/*.trace.json", "**/trace.json.gz")
        files = sorted({f for p in pats for f in glob.glob(
            str(proc_dir / p), recursive=True)})
        for fname in files:
            found = True
            for ev in _load_trace(fname):
                ev = dict(ev)
                if "pid" in ev:
                    try:
                        ev["pid"] = int(ev["pid"]) + idx * PID_STRIDE
                    except (TypeError, ValueError):
                        pass
                merged.append(ev)
    if not found:
        return None
    out_path = log_dir / out
    with gzip.open(out_path, "wt") as f:
        json.dump({"traceEvents": merged}, f)
    return out_path
