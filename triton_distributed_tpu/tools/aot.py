"""AOT compilation: serialize jitted programs, reload without retracing.

Reference: tools/compile.py + tools/compile_aot.py (:61-116 —
``aot_compile_spaces`` declares signature/grid spaces per kernel;
:183-460 — generated C sources + dispatcher over function pointers;
runtime tools/runtime/triton_aot_runtime.{h,cc} loads cubins via the
cuLibrary API) and the ``USE_TRITON_DISTRIBUTED_AOT`` toggle
(sp_flash_decode_layer.py:32-39).

TPU re-design: XLA already owns codegen, so AOT is ``jit(fn).lower()``
→ ``compile()`` → ``jax.export`` serialization. ``aot_compile_spaces``
maps a signature *space* (the reference's dict of shape variants) to a
set of serialized executables keyed by shape; ``AotLibrary`` is the
dispatcher that picks the artifact matching the call shapes — the role
of the generated C dispatcher. Artifacts are plain files, mmap-loaded
by the C++ store (csrc/aot_store.cpp) where present, with a pure-python
fallback.
"""

from __future__ import annotations

import hashlib
import json
import pathlib

import jax

from triton_distributed_tpu.tools.native import artifact_read, artifact_write


def _key(name: str, shapes) -> str:
    h = hashlib.sha256(json.dumps([name, shapes], sort_keys=True).encode())
    return h.hexdigest()[:24]


def _shapes_of(args):
    return [[list(a.shape), str(a.dtype)] for a in args]


def aot_compile(fn, example_args, *, name: str, cache_dir=".aot_cache"):
    """Serialize ``jit(fn)`` specialized to ``example_args``' shapes.

    Returns the artifact path. ≡ compile_aot.py generating one artifact
    per (signature × config) point.
    """
    cache_dir = pathlib.Path(cache_dir)
    cache_dir.mkdir(parents=True, exist_ok=True)
    exported = jax.export.export(jax.jit(fn))(*example_args)
    blob = exported.serialize()
    path = cache_dir / f"{name}-{_key(name, _shapes_of(example_args))}.jaxexp"
    artifact_write(str(path), blob)
    return path


def aot_load(path):
    """Reload a serialized program as a callable (no retracing; XLA
    compiles the embedded StableHLO for the local topology —
    ≡ CUDAModuleLoadData, triton_aot_runtime.cc:26-61)."""
    blob = artifact_read(str(path))
    exported = jax.export.deserialize(bytearray(blob))
    return jax.jit(exported.call)


class AotLibrary:
    """Shape-dispatching store of AOT artifacts for one function
    (≡ the generated dispatcher over function pointers,
    compile_aot.py:183-460)."""

    def __init__(self, fn, *, name: str, cache_dir=".aot_cache"):
        self.fn = fn
        self.name = name
        self.cache_dir = pathlib.Path(cache_dir)
        self._loaded: dict = {}
        # provenance: how many shape points came from disk artifacts vs
        # fell back to fresh JIT (lets callers/tests assert "no retrace")
        self.stats = {"artifact_loads": 0, "jit_fallbacks": 0}

    def compile(self, *example_args):
        path = aot_compile(
            self.fn, example_args, name=self.name, cache_dir=self.cache_dir
        )
        self._loaded[json.dumps(_shapes_of(example_args))] = aot_load(path)
        return path

    def __call__(self, *args):
        key = json.dumps(_shapes_of(args))
        loaded = self._loaded.get(key)
        if loaded is None:
            path = self.cache_dir / (
                f"{self.name}-{_key(self.name, _shapes_of(args))}.jaxexp"
            )
            if path.exists():
                loaded = aot_load(path)
                self.stats["artifact_loads"] += 1
            else:
                loaded = jax.jit(self.fn)   # fallback: JIT on miss
                self.stats["jit_fallbacks"] += 1
            self._loaded[key] = loaded
        return loaded(*args)


def aot_compile_spaces(fn, spaces, *, name: str, cache_dir=".aot_cache"):
    """Pre-build a signature space (≡ aot_compile_spaces decorator,
    compile_aot.py:61-116): ``spaces`` is a list of example-arg tuples;
    returns the populated :class:`AotLibrary`."""
    lib = AotLibrary(fn, name=name, cache_dir=cache_dir)
    for example in spaces:
        lib.compile(*example)
    return lib
