"""Serving CLI: prefill a prompt batch, then SP flash-decode generate.

The reference leaves serving orchestration to the caller (its surface
is the SP decode layer); this CLI completes the loop at L7: build a
preset model on the available mesh, run the one-pass prompt prefill
into the sequence-sharded KV caches, and greedy-decode through the
distributed flash-decode layer, reporting decode throughput.

Usage (any host; model sizes default to the tiny CI twins)::

    python -m triton_distributed_tpu.tools.generate \
        --preset tiny:llama_7b --batch 4 --prompt-len 64 --steps 32

On a multi-chip mesh run one process per host via launch.sh; the tp
axis spans all devices (decode KV is sequence-sharded over it).
"""

from __future__ import annotations

import argparse
import time


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--preset", default="tiny",
                   help="models.presets factory name (tiny, llama_7b, "
                        "llama_70b, mixtral_8x7b, deepseek_moe_16b; "
                        "tiny:<name> = the CI twin of <name>'s topology)")
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=64)
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--capacity", type=int, default=None,
                   help="KV cache capacity (default prompt+steps rounded up)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--watchdog-deadline", type=float, default=0.0,
                   help="seconds before a wedged collective launch aborts "
                        "the run with rank/semaphore diagnostics instead of "
                        "hanging (0 = watchdog off). Armed around the WHOLE "
                        "run so every build traces the heartbeat hooks in.")
    args = p.parse_args(argv)

    import contextlib

    from triton_distributed_tpu.runtime.watchdog import collective_watchdog

    # arm BEFORE any build: arming participates in config.interp_key, so
    # kernels built inside the context carry the heartbeat instrumentation
    # the deadline monitor needs
    guard = (
        collective_watchdog(deadline=args.watchdog_deadline)
        if args.watchdog_deadline > 0 else contextlib.nullcontext()
    )
    with guard:
        _run(args)


def _run(args) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from triton_distributed_tpu.models import Transformer, presets

    import inspect

    def _factories():
        return {
            n: f for n, f in vars(presets).items()
            if inspect.isfunction(f) and f.__module__ == presets.__name__
        }

    def _resolve(name):
        f = _factories().get(name)
        if f is None:
            raise SystemExit(
                f"unknown preset {name!r}; available: "
                f"{sorted(_factories())} (or tiny:<name>)"
            )
        return f

    if args.preset.startswith("tiny:"):
        cfg = presets.tiny(_resolve(args.preset.split(":", 1)[1])())
    else:
        cfg = _resolve(args.preset)()

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs), ("tp",))
    model = Transformer(cfg, mesh, "tp", ())
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, s),
        model.init(jax.random.PRNGKey(args.seed)),
        model.shardings(),
    )
    # serving weight quantization (preset-gated): expert matrices and
    # dense projections to int8 + per-channel scales, consumed in the
    # grouped-GEMM epilogue (the KV cache quantizes via init_cache
    # when the preset sets kv_quant)
    params = model.quantize_moe_weights(params)
    params = model.quantize_dense_weights(params)

    cap = args.capacity or -(-(args.prompt_len + args.steps) // 128) * 128
    prompt = jax.random.randint(
        jax.random.PRNGKey(args.seed + 1), (args.batch, args.prompt_len),
        0, cfg.vocab,
    )

    # compile-warm both phases on throwaway state so the timings below
    # measure execution, not trace+compile
    warm = model._prefill_jit(params, model.init_cache(args.batch, cap), prompt)
    jax.block_until_ready(warm[0])
    del warm  # cache-sized pytree — free it before the timed phases

    caches = model.init_cache(args.batch, cap)
    t0 = time.perf_counter()
    last_logits, caches, lens = model._prefill_jit(params, caches, prompt)
    jax.block_until_ready(last_logits)
    t_prefill = time.perf_counter() - t0

    first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    # LL workspaces for EP-MoE decode (None for dense presets / off-TPU)
    moe_state = model.init_decode_state(args.batch)
    # one warm step to exclude decode compile from the timing — on
    # THROWAWAY cache/lens buffers: the decode jits donate their cache
    # and lens arguments (in-place update), so warming on the live ones
    # would delete the buffers the timed run needs
    warm_c = model.init_cache(args.batch, cap)
    if moe_state is None:
        _, caches_w, lens_w = model._decode_jit(params, warm_c, lens + 0, first)
    else:
        # the state is donated per step — keep threading the returned one
        _, caches_w, lens_w, moe_state = model._decode_jit_state(
            params, warm_c, lens + 0, first, moe_state
        )
    jax.block_until_ready(lens_w)
    del warm_c, caches_w

    t0 = time.perf_counter()
    res = model.generate(
        params, caches, lens, first, args.steps, moe_state=moe_state
    )
    toks, caches, lens = res[:3]
    toks = np.asarray(toks)  # host fetch = the reliable fence
    t_decode = time.perf_counter() - t0

    tps = args.batch * args.steps / t_decode
    print(f"preset={args.preset} devices={len(devs)} "
          f"B={args.batch} prompt={args.prompt_len} steps={args.steps}")
    print(f"prefill: {t_prefill * 1e3:.1f} ms "
          f"({args.batch * args.prompt_len / t_prefill:.0f} tok/s)")
    print(f"decode:  {t_decode * 1e3:.1f} ms "
          f"({tps:.0f} tok/s, {t_decode / args.steps * 1e3:.2f} ms/step)")
    print("sample completion ids:", toks[0, : min(8, args.steps)].tolist())


if __name__ == "__main__":
    main()
