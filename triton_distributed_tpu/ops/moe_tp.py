"""MoE tensor-parallel overlap ops: AG-GroupGEMM and GroupGEMM-Reduce-RS.

Reference: python/triton_dist/kernels/nvidia/allgather_group_gemm.py —
AG of activations overlapped with a grouped GEMM whose tiles wait on
producer barriers (:420-498, sort_topk_ids_align_block_size :106), and
moe_reduce_rs.py — producer grouped GEMM scattering weighted expert
outputs (:362-467) into a consumer topk-reduce + reduce-scatter pipeline
(:468-622, orchestration :882-1020).

Two pipelines:

* **Overlapped (default inference path)**: the single-kernel streaming
  engines of kernels/moe_tp_fused.py — tokens expert-sorted per shard
  ride the ring while arrived shards stream through grouped-GEMM
  pipelines (grouped-GEMM tiles gated by shard-arrival DMA semaphores,
  the TPU translation of the reference's per-tile producer barriers).
  Entry points: :func:`align_routing_sharded`,
  :func:`ag_group_gemm_fused`, :func:`moe_reduce_rs_fused`,
  :func:`moe_tp_mlp_overlapped`.
* **Composed** (v1, kept as the training-capable/differentiable and
  correctness-reference path): gather leg on ``lax.all_gather``, reduce
  leg on the Pallas ring reduce-scatter, grouped GEMM via the
  scalar-prefetch Mosaic kernel.

Layouts (Megatron MoE-TP):

* ``ag_group_gemm``: tokens row-sharded over TP → gathered; experts'
  up-projection weights column-sharded (E, K, N/tp). Output: sorted
  expert rows (cap, N/tp), plus the routing artifacts needed downstream.
* ``moe_reduce_rs``: sorted expert rows (cap, F/tp)? No — the dual:
  down-projection weights row-sharded (E, F/tp, H) so each rank's
  grouped GEMM yields a PARTIAL (cap, H); the topk-weighted combine to
  token order is also partial, and the reduce-scatter both sums the TP
  partials and returns each rank its token rows.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.kernels.group_gemm import (
    grouped_matmul,
    grouped_matmul_xla,
    padded_splits,
)
from triton_distributed_tpu.kernels.reduce_scatter import reduce_scatter


@dataclass(frozen=True)
class MoETPContext:
    """Static geometry of the MoE TP pipeline (≡ the contexts built by
    create_ag_group_gemm_context, allgather_group_gemm.py:272-330, and
    MoEReduceRSContext, moe_reduce_rs.py:253-360)."""

    mesh: Mesh
    axis: str
    num_experts: int
    topk: int
    block_m: int = 128
    dtype: jnp.dtype = jnp.bfloat16
    use_pallas_gemm: bool = True
    rs_collective_id: int = 12
    ag_collective_id: int = 13
    batch_axes: tuple = ()          # extra (DP) axes sharding token rows
    # Quantized ring wire for the OVERLAPPED engines (lang.wire):
    # 'fp8'/'int8' ships the sorted token slabs (AG side, quantized once
    # at the source) and the per-hop partials (RS side, f32 dequant-
    # accumulate) as 1-byte payloads + per-chunk scales. 'int8-mxu'
    # ends the AG wire at the MXU: arriving int8 slabs feed the s8×s8
    # grouped GEMM against per-(expert, out-channel) quantized weights
    # with the scales folded in the accumulator epilogue — no
    # per-arrival dequant pass (the RS side then carries the int8
    # payload wire). None → bf16 wire. Explicit opt-in (no 'auto' here
    # — the MoE context is static configuration, like its quant= twin
    # on the EP transport).
    wire_dtype: str | None = None

    @property
    def row_spec(self):
        return P(tuple(self.batch_axes) + (self.axis,))

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_group_gemm_context(mesh, axis, *, num_experts, topk, **kw):
    """≡ create_ag_group_gemm_context (allgather_group_gemm.py:272)."""
    return MoETPContext(
        mesh=mesh, axis=axis, num_experts=num_experts, topk=topk, **kw
    )


def create_moe_rs_context(mesh, axis, *, num_experts, topk, **kw):
    """≡ create_moe_rs_context (moe_reduce_rs.py:253)."""
    return MoETPContext(
        mesh=mesh, axis=axis, num_experts=num_experts, topk=topk, **kw
    )


def _ggemm(ctx: MoETPContext, xs, w, be, counts, cap):
    if ctx.use_pallas_gemm:
        return grouped_matmul(xs, w, be, block_m=ctx.block_m)
    return grouped_matmul_xla(xs, w, padded_splits(counts, ctx.block_m, cap))


def align_routing(ctx: MoETPContext, topk_ids):
    """Routing alignment shared by both pipeline stages: returns
    (sorted_token_ids, block_expert, splits) from moe_align_block_size.
    Compute ONCE per step and thread through ag_group_gemm and
    moe_reduce_rs — both stages need the identical layout, and the
    stable argsort is the expensive part (≡ the single
    sort_topk_ids_align_block_size call at allgather_group_gemm.py:106).
    """
    return mu.moe_align_block_size(topk_ids, ctx.num_experts, ctx.block_m)


def ag_group_gemm_device(a_loc, sti, be, counts, w_loc, ctx: MoETPContext):
    """Per-device body: gather tokens, grouped GEMM over sorted layout.

    a_loc: (M/tp, K) this rank's token rows; sti/be/counts: REPLICATED
    routing from :func:`align_routing`; w_loc: (E, K, N/tp) this rank's
    expert weight columns. Returns (cap, N/tp) sorted expert rows.
    """
    a_full = jax.lax.all_gather(a_loc, ctx.axis, tiled=True)   # (M, K)
    xs = mu.gather_sorted(a_full, sti, ctx.topk).astype(ctx.dtype)
    return _ggemm(ctx, xs, w_loc.astype(ctx.dtype), be, counts, sti.shape[0])


@functools.lru_cache(maxsize=64)
def _build_ag_group_gemm(ctx: MoETPContext):
    fn = jax.shard_map(
        functools.partial(ag_group_gemm_device, ctx=ctx),
        mesh=ctx.mesh,
        in_specs=(P(ctx.axis), P(), P(), P(), P(None, None, ctx.axis)),
        out_specs=P(None, ctx.axis),
        check_vma=False,
    )
    return jax.jit(fn)


def ag_group_gemm(a, routing, w, ctx: MoETPContext):
    """Host entry (≡ ag_group_gemm, allgather_group_gemm.py:272-420).

    a: (M, K) token rows sharded over ``ctx.axis``; routing: the
    replicated (sti, be, counts) triple from :func:`align_routing`;
    w: (E, K, N) with N sharded. Returns (cap, N) sorted expert rows
    with N sharded.
    """
    assert ctx.batch_axes == (), (
        "composed ag_group_gemm reshards tokens tp-only; with DP use "
        "moe_tp_mlp (which honors batch_axes) or the overlapped entries "
        "inside your own DP shard_map"
    )
    sti, be, counts = routing
    return _build_ag_group_gemm(ctx)(a, sti, be, counts, w)


def moe_reduce_rs(y, routing, weights, w, ctx: MoETPContext):
    """Host entry (≡ moe_reduce_rs, moe_reduce_rs.py:882-1020).

    y: (cap, F) sorted expert rows with F sharded over ``ctx.axis``;
    routing: the same triple passed to :func:`ag_group_gemm`; weights:
    (M, k) replicated router weights; w: (E, F, H) with F sharded.
    Returns (M, H) token rows sharded over ``ctx.axis``.
    """
    assert ctx.batch_axes == (), (
        "composed moe_reduce_rs reshards tokens tp-only; with DP use "
        "moe_tp_mlp (which honors batch_axes) or the overlapped entries "
        "inside your own DP shard_map"
    )
    sti, be, counts = routing
    return _build_moe_reduce_rs(ctx)(y, sti, be, counts, weights, w)


@functools.lru_cache(maxsize=64)
def _build_moe_reduce_rs(ctx: MoETPContext):
    def body(y_loc, sti, be, counts, weights, w_loc):
        part = _ggemm(
            ctx, y_loc.astype(ctx.dtype), w_loc.astype(ctx.dtype),
            be, counts, sti.shape[0],
        )                                                    # (cap, H) partial
        m = weights.shape[0]
        tok = mu.scatter_combine(part, sti, weights, m)      # (M, H) partial
        return tok.astype(ctx.dtype)[None]                   # stack dim for RS

    inner = jax.shard_map(
        body,
        mesh=ctx.mesh,
        in_specs=(P(None, ctx.axis), P(), P(), P(), P(), P(None, ctx.axis)),
        out_specs=P(ctx.axis, None, None),
        check_vma=False,
    )

    def entry(y, sti, be, counts, weights, w):
        # shard_map body returns per-rank partials laid out (tp, M, H);
        # the ring reduce-scatter sums them and scatters token rows
        parts = inner(y, sti, be, counts, weights, w)
        return reduce_scatter(
            parts, ctx.mesh, ctx.axis,
            collective_id=ctx.rs_collective_id, stacked=True,
        )

    return jax.jit(entry)


# ------------------------------------------------- overlapped (fused) path


@dataclass(frozen=True)
class ShardedRouting:
    """Per-shard routing tables for the overlapped pipeline: shard ``s``'s
    tokens in shard-local expert-sorted order. All replicated."""

    sti: jax.Array      # (tp, cap_s) shard-local sorted token ids
    be: jax.Array       # (tp, cap_s / block_m) block→expert table
    splits: jax.Array   # (tp, E) true per-expert counts per shard

    @property
    def cap_s(self) -> int:
        return self.sti.shape[1]


def align_routing_sharded(ctx: MoETPContext, topk_ids) -> ShardedRouting:
    """Per-SHARD routing alignment for the overlapped engines.

    ``topk_ids``: (M, k) replicated. Shard ``s`` owns token rows
    [s·M/tp, (s+1)·M/tp); each shard is aligned independently so its
    sorted slab is self-contained (the slab IS the ring payload).
    """
    m, k = topk_ids.shape
    assert m % ctx.tp == 0
    ids_s = jnp.asarray(topk_ids).reshape(ctx.tp, m // ctx.tp, k)
    sti, be, splits = jax.vmap(
        lambda i: mu.moe_align_block_size(i, ctx.num_experts, ctx.block_m)
    )(ids_s)
    return ShardedRouting(sti=sti, be=be, splits=splits)


def _fused_blocks(ctx: MoETPContext, cap_s: int, k: int, nl: int):
    from triton_distributed_tpu.kernels.moe_tp_fused import pick_gg_blocks

    blocks = pick_gg_blocks(
        ctx.block_m, cap_s, k, nl, jnp.dtype(ctx.dtype).itemsize
    )
    if blocks is None:
        raise ValueError(
            f"overlapped MoE-TP: no lowerable blocking for block_m="
            f"{ctx.block_m}, cap_s={cap_s}, K={k}, N={nl} — adjust "
            "block_m (TPU needs a sublane multiple) or use the composed path"
        )
    return blocks


@functools.lru_cache(maxsize=64)
def _build_gather_sorted(ctx: MoETPContext, m_shard: int):
    def body(x_loc, sti):
        me = jax.lax.axis_index(ctx.axis)
        return mu.gather_sorted(x_loc, sti[me], ctx.topk).astype(ctx.dtype)

    fn = jax.shard_map(
        body, mesh=ctx.mesh, in_specs=(P(ctx.axis), P()),
        out_specs=P(ctx.axis), check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _build_ag_gg_fused(ctx: MoETPContext, cap_s, k, nl_local):
    from triton_distributed_tpu.kernels.moe_tp_fused import (
        _wire_fmt,
        build_ag_group_gemm_call,
    )

    blocks = _fused_blocks(ctx, cap_s, k, nl_local)
    call = build_ag_group_gemm_call(
        ctx.tp, ctx.mesh.axis_names, ctx.axis, cap_s, k, nl_local,
        ctx.num_experts, blocks, jnp.dtype(ctx.dtype), ctx.ag_collective_id,
        wire=ctx.wire_dtype,
    )
    if ctx.wire_dtype is None:
        body = lambda be, xs, w: call(be, xs, w)[0]  # noqa: E731
    elif ctx.wire_dtype == "int8-mxu":
        from triton_distributed_tpu.kernels.group_gemm import (
            quantize_grouped_weights,
        )
        from triton_distributed_tpu.lang import wire as wirelib

        fmt = _wire_fmt(ctx.wire_dtype, cap_s, blocks[0])

        def body(be, xs, w):
            # both operands quantized once in XLA; the kernel consumes
            # wire bytes end to end (scales fold in the GEMM epilogue)
            xq, xsc = wirelib.quantize_slab(xs, fmt)
            wq, wsc = quantize_grouped_weights(w, "int8")
            return call(be, xq, xsc, wq, wsc[:, None, :])[0]
    else:
        from triton_distributed_tpu.lang import wire as wirelib

        fmt = _wire_fmt(ctx.wire_dtype, cap_s)

        def body(be, xs, w):
            xq, xsc = wirelib.quantize_slab(xs, fmt)
            return call(be, xs, xq, xsc, w)[0]
    fn = jax.shard_map(
        body,
        mesh=ctx.mesh,
        in_specs=(P(), P(ctx.axis), P(None, None, ctx.axis)),
        out_specs=P(None, ctx.axis),
        check_vma=False,
    )
    return jax.jit(fn)


def ag_group_gemm_fused(x, routing: ShardedRouting, w, ctx: MoETPContext):
    """Overlapped AG-GroupGEMM (default inference engine; ≡ ag_group_gemm,
    allgather_group_gemm.py:272-498, with the producer barriers replaced
    by shard-arrival DMA semaphores — see kernels/moe_tp_fused.py).

    x: (M, K) token rows sharded over ``ctx.axis``; w: (E, K, N) with N
    sharded. Returns (tp·cap_s, N) per-shard sorted rows, N sharded.
    """
    assert ctx.batch_axes == (), (
        "overlapped MoE-TP runs per DP replica; wrap it in your own "
        "shard_map over batch axes or use moe_tp_mlp"
    )
    m, k = x.shape
    xs = _build_gather_sorted(ctx, m // ctx.tp)(x, routing.sti)
    return _build_ag_gg_fused(ctx, routing.cap_s, k, w.shape[2] // ctx.tp)(
        routing.be, xs, w
    )


@functools.lru_cache(maxsize=64)
def _build_moe_rs_fused(ctx: MoETPContext, cap_s, fl_local, h):
    from triton_distributed_tpu.kernels.moe_tp_fused import (
        build_moe_reduce_rs_call,
    )

    blocks = _fused_blocks(ctx, cap_s, fl_local, h)
    call = build_moe_reduce_rs_call(
        ctx.tp, ctx.mesh.axis_names, ctx.axis, cap_s, fl_local, h,
        ctx.num_experts, blocks, jnp.dtype(ctx.dtype), ctx.rs_collective_id,
        wire=ctx.wire_dtype,
    )
    fn = jax.shard_map(
        lambda be, y, w: call(be, y, w)[0],
        mesh=ctx.mesh,
        in_specs=(P(), P(None, ctx.axis), P(None, ctx.axis, None)),
        out_specs=P(ctx.axis),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _build_combine(ctx: MoETPContext, m_shard: int):
    def body(red_loc, sti, w_loc):
        me = jax.lax.axis_index(ctx.axis)
        out = mu.scatter_combine(red_loc, sti[me], w_loc, m_shard)
        return out.astype(ctx.dtype)

    fn = jax.shard_map(
        body, mesh=ctx.mesh, in_specs=(P(ctx.axis), P(), P(ctx.axis)),
        out_specs=P(ctx.axis), check_vma=False,
    )
    return jax.jit(fn)


def moe_reduce_rs_fused(y, routing: ShardedRouting, weights, w,
                        ctx: MoETPContext):
    """Overlapped GroupGEMM-Reduce-RS (default inference engine;
    ≡ moe_reduce_rs, moe_reduce_rs.py:362-1020: the producer grouped
    GEMM computes straight into the reduce ring).

    y: (tp·cap_s, F) per-shard sorted rows from
    :func:`ag_group_gemm_fused` (post-activation), F sharded; weights:
    (M, k) router weights sharded over ``ctx.axis`` rows; w: (E, F, H)
    with F sharded. Returns (M, H) token rows sharded over ``ctx.axis``.
    """
    assert ctx.batch_axes == (), (
        "overlapped MoE-TP runs per DP replica; wrap it in your own "
        "shard_map over batch axes or use moe_tp_mlp"
    )
    assert y.shape[0] == ctx.tp * routing.cap_s
    red = _build_moe_rs_fused(
        ctx, routing.cap_s, y.shape[1] // ctx.tp, w.shape[2]
    )(routing.be, y, w)
    m = weights.shape[0]
    return _build_combine(ctx, m // ctx.tp)(red, routing.sti, weights)


def moe_tp_mlp_overlapped(x, topk_ids, topk_weights, w_up, w_down,
                          ctx: MoETPContext, activation: str = "silu"):
    """Full overlapped TP MoE MLP: AG⊕up-GroupGEMM → act → down-GroupGEMM
    ⊕Reduce-RS. The default inference path; the composed
    :func:`moe_tp_mlp` remains the differentiable training path."""
    from triton_distributed_tpu.ops.moe import _act

    routing = align_routing_sharded(ctx, topk_ids)
    h = ag_group_gemm_fused(x, routing, w_up, ctx)
    h = _act(activation, h.astype(jnp.float32)).astype(ctx.dtype)
    return moe_reduce_rs_fused(h, routing, topk_weights, w_down, ctx)


def moe_tp_mlp_device(
    x_loc, ids_loc, weights_loc, w_up_loc, w_down_loc,
    ctx: MoETPContext, activation: str = "silu",
):
    """Fused per-replica body: AG → route → grouped up/act/down → RS.

    Inside a shard_map over (*batch_axes, axis): gathers this replica's
    tokens and routing over ``axis``, sorts once, runs both grouped
    GEMMs (up col-sharded, down row-sharded → partial), combines
    topk-weighted token rows, and ``psum_scatter``s the partials so
    each rank ends with its token shard. Differentiable end to end —
    the training-capable TP MoE (the composed ag_group_gemm /
    moe_reduce_rs pair with the Pallas ring RS is the inference path).
    """
    x_full = jax.lax.all_gather(x_loc, ctx.axis, tiled=True)       # (M, K)
    ids = jax.lax.all_gather(ids_loc, ctx.axis, tiled=True)        # (M, k)
    weights = jax.lax.all_gather(weights_loc, ctx.axis, tiled=True)
    sti, be, counts = mu.moe_align_block_size(
        ids, ctx.num_experts, ctx.block_m
    )
    cap = sti.shape[0]
    from triton_distributed_tpu.ops.moe import _act

    xs = mu.gather_sorted(x_full, sti, ctx.topk).astype(ctx.dtype)
    h = _ggemm(ctx, xs, w_up_loc.astype(ctx.dtype), be, counts, cap)
    h = _act(activation, h).astype(ctx.dtype)
    part = _ggemm(ctx, h, w_down_loc.astype(ctx.dtype), be, counts, cap)
    tok = mu.scatter_combine(part, sti, weights, x_full.shape[0])
    return jax.lax.psum_scatter(
        tok, ctx.axis, scatter_dimension=0, tiled=True
    ).astype(ctx.dtype)


@functools.lru_cache(maxsize=64)
def _build_moe_tp_mlp(ctx: MoETPContext, activation: str):
    rows = ctx.row_spec
    fn = jax.shard_map(
        functools.partial(moe_tp_mlp_device, ctx=ctx, activation=activation),
        mesh=ctx.mesh,
        in_specs=(rows, rows, rows,
                  P(None, None, ctx.axis), P(None, ctx.axis)),
        out_specs=rows,
        check_vma=False,
    )
    return jax.jit(fn)


def moe_tp_mlp(x, topk_ids, topk_weights, w_up, w_down, ctx: MoETPContext,
               activation: str = "silu"):
    """Host entry for the fused TP MoE MLP.

    x (M, K), topk_ids/topk_weights (M, k): all row-sharded over
    (*batch_axes, axis) — per-DP-replica routing; w_up (E, K, F) with F
    sharded; w_down (E, F, H) with F sharded. Returns (M, H)
    row-sharded like ``x``.
    """
    return _build_moe_tp_mlp(ctx, activation)(
        x, topk_ids, topk_weights, w_up, w_down
    )
