"""Op layer: differentiable, context-managed distributed ops.

≡ the reference's public kernel API (python/triton_dist/kernels/nvidia/
__init__.py:25-40: ag_gemm, gemm_rs, fast_all_to_all, … +
create_*_context factories), with autodiff added so the same ops serve
training, not just inference.
"""

from triton_distributed_tpu.ops.moe import (
    EPMoEContext,
    EPMoEState,
    create_ep_moe_context,
    create_ep_moe_state,
    ep_moe,
    ep_moe_device,
    ep_moe_tuned,
)
from triton_distributed_tpu.ops.moe_tp import (
    MoETPContext,
    ShardedRouting,
    ag_group_gemm,
    ag_group_gemm_fused,
    align_routing,
    align_routing_sharded,
    create_ag_group_gemm_context,
    create_moe_rs_context,
    moe_reduce_rs,
    moe_reduce_rs_fused,
    moe_tp_mlp,
    moe_tp_mlp_overlapped,
)
from triton_distributed_tpu.ops.overlap import (
    OverlapContext,
    ag_gemm,
    ag_gemm_safe,
    create_ag_gemm_context,
    create_gemm_rs_context,
    gemm_rs,
    gemm_rs_safe,
    preflight,
    with_fallback,
)

__all__ = [
    "OverlapContext",
    "ag_gemm",
    "gemm_rs",
    "ag_gemm_safe",
    "gemm_rs_safe",
    "preflight",
    "with_fallback",
    "create_ag_gemm_context",
    "create_gemm_rs_context",
    "EPMoEContext",
    "EPMoEState",
    "create_ep_moe_state",
    "ep_moe",
    "ep_moe_device",
    "ep_moe_tuned",
    "create_ep_moe_context",
    "MoETPContext",
    "ShardedRouting",
    "ag_group_gemm",
    "ag_group_gemm_fused",
    "align_routing",
    "align_routing_sharded",
    "moe_reduce_rs",
    "moe_reduce_rs_fused",
    "moe_tp_mlp",
    "moe_tp_mlp_overlapped",
    "create_ag_group_gemm_context",
    "create_moe_rs_context",
]
