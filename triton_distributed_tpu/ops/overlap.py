"""Differentiable overlap ops: context-managed AG-GEMM / GEMM-RS.

Reference API surface: ``triton_dist.kernels`` exposes ``ag_gemm`` /
``gemm_rs`` plus ``create_*_context`` factories
(python/triton_dist/kernels/nvidia/__init__.py:25-40;
AllGatherGEMMTensorParallelContext allgather_gemm.py:407-490;
create_gemm_rs_context gemm_reduce_scatter.py:41-87). The reference is
inference-only (torch, no autograd through the kernels); here the ops are
differentiable, which is what makes the flagship *training* path possible:

* d(AG-GEMM): dA = GEMM-RS(dC, Bᵀ); dB = psum_dp(AG(A)ᵀ @ dC)
* d(GEMM-RS): dA = AG-GEMM(dC, Bᵀ); dB = psum_dp(Aᵀ @ AG(dC))

i.e. the backward of each overlap op's *activation gradient* is the dual
overlap op, so dA gets the same compute/communication overlap as the
forward — a property the stream-based reference design cannot express.

The weight gradients overlap too:

* gemm_rs: the dual ag_gemm that computes dA produces the gathered dC
  as a free by-product of its ring (``return_gathered=True``), so dB is
  a plain local matmul — its AllGather rode the fused dA engine.
* ag_gemm: with ``ctx.save_gathered`` (default) the FORWARD fused
  engine's gathered-A output is kept as the residual, so dB needs no
  gather at all — the AG cost sits in the forward where the engine
  hides it under the GEMM. Costs tp× more residual memory for that
  tensor; set ``save_gathered=False`` to re-gather in backward instead
  (plain all_gather + matmul).

Backward wire: ``ctx.bwd_wire_dtype`` puts the DUAL rings on the
compressed wire too — dA's GEMM-RS / AG-GEMM ride
``train.grad_wire``'s error-feedback + stochastic-rounding rings
(1-byte payload + scale column) instead of the exact bf16 duals. Same
resolve vocabulary and refusal contract as the forward ``wire_dtype``;
``None`` (default) keeps the duals byte-identical to before.
"""

from __future__ import annotations

import functools
import logging
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod, ag_gemm as _ag_gemm_raw
from triton_distributed_tpu.kernels.gemm_rs import GemmRSMethod, gemm_rs as _gemm_rs_raw


def _dual_method(method, target_enum):
    """Map a pinned engine onto the dual op's enum (the backward of ag_gemm
    is a gemm_rs and vice versa; the enums share member names). None stays
    None (auto-select)."""
    if method is None:
        return None
    return target_enum[method.name]


@dataclass(frozen=True)
class OverlapContext:
    """Shared context for the TP overlap ops (≡ the reference's
    per-op *Context dataclasses, which own symmetric workspaces/streams;
    on TPU the state that must persist is just mesh/axis/method/ids)."""

    mesh: Mesh
    axis: str = "x"
    batch_axes: tuple = ()
    method: object = None          # AGGemmMethod / GemmRSMethod / None=auto
    out_dtype: object = None
    collective_id: int = 8
    # Quantized ring wire for the FORWARD op (lang.wire): None/'bf16',
    # 'fp8', 'int8', or 'auto' (perf-model/tuner comm-bound selection).
    wire_dtype: object = None
    # Quantized ring wire for the BACKWARD duals (train.grad_wire) —
    # same vocabulary, same resolve contract as the forward knob:
    # 'auto' demotes SILENTLY when the cotangent slab admits no ring
    # chunking; a pinned 'fp8'/'int8' that cannot be carried RAISES at
    # backward trace time (a pinned wire format is a contract, not a
    # hint). The dual rings ship seeded stochastic rounding + per-hop
    # error feedback so accumulated gradient error stays bounded across
    # the n-1 hops (docs/TRAINING.md). None (default) keeps the exact
    # bf16 duals.
    bwd_wire_dtype: object = None
    # ag_gemm training: keep the forward engine's gathered-A output as
    # the VJP residual so the weight gradient is gather-free (see module
    # docstring). tp× residual memory for A; disable to re-gather in bwd.
    # Only engages when the FUSED engine resolves (an XLA engine would
    # pay a second standalone all_gather just to produce the residual).
    save_gathered: bool = True

    def __post_init__(self):
        # fail fast (at context build, not deep in a backward trace) on
        # a bwd wire string outside the lang.wire vocabulary
        from triton_distributed_tpu.lang import wire as wirelib

        wirelib.normalize_wire(self.bwd_wire_dtype)

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_gemm_context(mesh, axis="x", **kw) -> OverlapContext:
    """≡ reference create_ag_gemm_context (allgather_gemm.py:490-537)."""
    return OverlapContext(mesh=mesh, axis=axis, **kw)


def create_gemm_rs_context(mesh, axis="x", **kw) -> OverlapContext:
    """≡ reference create_gemm_rs_context (gemm_reduce_scatter.py:41-87)."""
    kw.setdefault("collective_id", 9)
    return OverlapContext(mesh=mesh, axis=axis, **kw)


def _psum_if(x, axes):
    return jax.lax.psum(x, axes) if axes else x


def _resolve_bwd(ctx: OverlapContext, g, cols: int):
    """The wire the backward dual ring will ACTUALLY ship for cotangent
    ``g`` — None (exact bf16 duals, the historical path) or a concrete
    'fp8'/'int8'. ``cols`` is the dual ring slab's column count (K for
    ag_gemm's dA reduce-scatter, N for gemm_rs's dA all-gather). A
    pinned-but-uncarryable bwd wire raises here, at backward trace
    time — loud, per the resolve_*_wire contract."""
    if ctx.bwd_wire_dtype is None:
        return None
    from triton_distributed_tpu.runtime import mesh_axes_size
    from triton_distributed_tpu.train import grad_wire

    dp = mesh_axes_size(ctx.mesh, tuple(ctx.batch_axes))
    return grad_wire.resolve_grad_wire(
        ctx.bwd_wire_dtype, g.shape[0] // dp, cols, ctx.tp
    )


@functools.lru_cache(maxsize=256)
def _build_ag_wgrad(mesh, axis, batch_axes):
    """dB for ag_gemm when the gathered A was NOT saved:
    psum_dp( AG(A)ᵀ @ dC ) — weight grads reduce over the data-parallel
    axes, activations gather over the TP axis."""
    ba = tuple(batch_axes)

    def body(a_loc, g_loc):
        a_full = jax.lax.all_gather(a_loc, axis, tiled=True)
        db = jnp.dot(
            a_full.T.astype(jnp.float32), g_loc.astype(jnp.float32)
        )
        return _psum_if(db, ba)

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ba + (axis,) if ba else axis, None), P(ba if ba else None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return jax.jit(fn)


# (the former _build_rs_wgrad — gather-in-backward dB for gemm_rs — is
# subsumed by _build_gathered_wgrad: the dual dA op now supplies AG(dC))
@functools.lru_cache(maxsize=256)
def _build_gathered_wgrad(mesh, axis, batch_axes, transpose_out):
    """Gather-free dB from an already-gathered operand:
    psum_dp( fullᵀ @ loc ) with out cols sharded (``transpose_out=False``
    — ag_gemm's dB (K, N/tp)) or psum_dp( locᵀ @ full ) with out rows
    sharded (``True`` — gemm_rs's dB (K/tp, N)). The AllGather that fed
    ``full`` rode a fused engine (forward's return_gathered, or the dual
    dA op's ring), so this is pure local compute."""
    ba = tuple(batch_axes)
    full_spec = P(ba if ba else None, None)
    loc_spec = P(ba if ba else None, axis)

    if transpose_out:
        def body(a_loc, g_full):
            return _psum_if(
                jnp.dot(a_loc.T.astype(jnp.float32), g_full.astype(jnp.float32)),
                ba,
            )

        in_specs, out_specs = (loc_spec, full_spec), P(axis, None)
    else:
        def body(a_full, g_loc):
            return _psum_if(
                jnp.dot(a_full.T.astype(jnp.float32), g_loc.astype(jnp.float32)),
                ba,
            )

        in_specs, out_specs = (full_spec, loc_spec), P(None, axis)

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def ag_gemm(a, b, ctx: OverlapContext):
    """Differentiable AllGather(A) @ B (column-parallel / SP layout).

    ``a``: (M, K) rows sharded (*batch_axes, axis); ``b``: (K, N) cols
    sharded ``axis``. Returns (M, N) rows batch-sharded, cols axis-sharded.
    """
    return _ag_gemm_raw(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=ctx.method,
        out_dtype=ctx.out_dtype, collective_id=ctx.collective_id,
        wire_dtype=ctx.wire_dtype,
    )


def _fused_forward(ctx, a, b) -> bool:
    """Gate for saving the gathered A: only the fused engine emits it
    for free (an XLA engine would pay a SECOND standalone all_gather for
    the residual, plus tp× residual memory, while saving nothing).

    Deliberately a PURE function of (ctx, global shapes, dtype): the
    explicit ctx.method, else the topology/blockability heuristic —
    never the tuner, whose answer differs between traced and concrete
    calls and would let fwd and bwd disagree about what the residual is.
    When the gate passes, the forward PINS method=PALLAS_FUSED so the
    engine that runs is exactly the one the gate promised."""
    from triton_distributed_tpu.kernels.ag_gemm import auto_ag_gemm_method
    from triton_distributed_tpu.runtime import mesh_axes_size

    method = ctx.method
    if method is None:
        method = auto_ag_gemm_method(
            ctx.mesh, ctx.axis, a, b,
            dp=mesh_axes_size(ctx.mesh, tuple(ctx.batch_axes)),
        )
    return method == AGGemmMethod.PALLAS_FUSED


def _ag_gemm_fwd(a, b, ctx):
    # NOTE: the save/no-save decision is a pure function of (ctx, global
    # shapes, dtype) — the backward recomputes it from the residuals
    # (same global shapes) instead of carrying a flag, which would turn
    # into a tracer across the fwd/bwd boundary under jit.
    if ctx.save_gathered and _fused_forward(ctx, a, b):
        # the fused engine emits the gathered A as a by-product of its
        # ring; saving it makes the backward dB gather-free (the AG cost
        # lives in the forward, hidden under the forward GEMM)
        out, a_full = _ag_gemm_raw(
            a, b, ctx.mesh, ctx.axis,
            batch_axes=ctx.batch_axes,
            # pinned: the engine must be the one the gate promised (see
            # _fused_forward) — a tuner pick here could silently be XLA
            method=AGGemmMethod.PALLAS_FUSED,
            out_dtype=ctx.out_dtype, collective_id=ctx.collective_id,
            return_gathered=True, wire_dtype=ctx.wire_dtype,
        )
        return out, (a_full, b)
    return ag_gemm(a, b, ctx), (a, b)


def _ag_gemm_bwd(ctx, res, g):
    a_res, b = res
    # dA: the dual overlap op — GEMM(dC, Bᵀ) fused with ReduceScatter.
    # With a resolved bwd wire the reduce-scatter runs on the EF +
    # stochastic-rounding quantized ring instead of the exact dual.
    wire = _resolve_bwd(ctx, g, b.shape[0])
    if wire is not None:
        from triton_distributed_tpu.train import grad_wire

        da = grad_wire.ef_gemm_rs(
            g, b.T, ctx.mesh, ctx.axis,
            batch_axes=ctx.batch_axes, out_dtype=a_res.dtype,
            wire=wire,
            seed=grad_wire.derive_seed(ctx.collective_id, "ag_gemm.bwd"),
        )
    else:
        da = _gemm_rs_raw(
            g, b.T, ctx.mesh, ctx.axis,
            batch_axes=ctx.batch_axes,
            method=_dual_method(ctx.method, GemmRSMethod),
            out_dtype=a_res.dtype, collective_id=ctx.collective_id + 1,
        )
    ba = tuple(ctx.batch_axes)
    if ctx.save_gathered and _fused_forward(ctx, a_res, b):
        # a_res is the forward-saved gathered A (same global shape as a)
        db = _build_gathered_wgrad(ctx.mesh, ctx.axis, ba, False)(a_res, g)
    else:
        db = _build_ag_wgrad(ctx.mesh, ctx.axis, ba)(a_res, g)
    return da, db.astype(b.dtype)


ag_gemm.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gemm_rs(a, b, ctx: OverlapContext):
    """Differentiable (A @ B) → ReduceScatter (row-parallel / SP layout).

    ``a``: (M, K) rows batch-sharded, cols sharded ``axis``; ``b``: (K, N)
    rows sharded ``axis``. Returns (M, N) rows sharded (*batch_axes, axis).
    """
    return _gemm_rs_raw(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=ctx.method,
        out_dtype=ctx.out_dtype, collective_id=ctx.collective_id,
        wire_dtype=ctx.wire_dtype,
    )


def _gemm_rs_fwd(a, b, ctx):
    return gemm_rs(a, b, ctx), (a, b)


def _gemm_rs_bwd(ctx, res, g):
    a, b = res
    # dA: the dual overlap op — AllGather(dC) fused with GEMM(·, Bᵀ).
    # Its ring gathers dC as a free by-product (return_gathered), which
    # is exactly the AG(dC) the weight gradient needs: dB becomes a
    # local matmul with no collective of its own. With a resolved bwd
    # wire the gather ships quantize-once stochastic-rounded bytes and
    # g_full is their dequantization — dB sees the same wire error dA
    # does, by construction.
    wire = _resolve_bwd(ctx, g, g.shape[1])
    if wire is not None:
        from triton_distributed_tpu.train import grad_wire

        da, g_full = grad_wire.ef_ag_gemm(
            g, b.T, ctx.mesh, ctx.axis,
            batch_axes=ctx.batch_axes, out_dtype=a.dtype,
            wire=wire,
            seed=grad_wire.derive_seed(ctx.collective_id, "gemm_rs.bwd"),
            return_gathered=True,
        )
    else:
        da, g_full = _ag_gemm_raw(
            g, b.T, ctx.mesh, ctx.axis,
            batch_axes=ctx.batch_axes,
            method=_dual_method(ctx.method, AGGemmMethod),
            out_dtype=a.dtype, collective_id=ctx.collective_id + 1,
            return_gathered=True,
        )
    db = _build_gathered_wgrad(
        ctx.mesh, ctx.axis, tuple(ctx.batch_axes), True
    )(a, g_full)
    return da, db.astype(b.dtype)


gemm_rs.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


# ------------------------------------------------------ graceful degradation

logger = logging.getLogger(__name__)
_demotions_logged: set = set()


def _log_demotion_once(engine: str, reason: str) -> None:
    key = (engine, reason.split("(")[0])
    if key not in _demotions_logged:
        _demotions_logged.add(key)
        logger.warning(
            "%s: demoting fused engine to its XLA-native fallback — %s "
            "(logged once per engine/reason)", engine, reason,
        )


def preflight(ctx: OverlapContext, engine: str, a, b) -> str | None:
    """Why the fused ``engine`` must NOT run for these arguments — or
    None when it is safe. Checked conditions, in order:

    * the active :class:`~triton_distributed_tpu.runtime.faults.FaultPlan`
      marks peers unhealthy (a fused single-kernel ring has no way to
      route around a failed peer — the XLA path at least fails fast and
      collectively);
    * the collective watchdog tripped on a prior step (whatever wedged
      once will wedge again until an operator intervenes — clear with
      ``runtime.watchdog.clear_trip()`` after recovery);
    * the VMEM/blockability probe: the shape admits no Mosaic blocking
      under the current ``TDTPU_FUSED_VMEM_BUDGET``, or the environment
      cannot execute Pallas collectives at all (both folded into the
      engine's own auto heuristic, reused here so the probe and the
      dispatcher can never disagree).
    """
    from triton_distributed_tpu.runtime import faults, watchdog
    from triton_distributed_tpu.runtime import mesh_axes_size

    plan = faults.active_plan()
    if plan is not None and plan.unhealthy_peers:
        return (
            f"fault plan marks peer(s) {plan.unhealthy_peers} unhealthy "
            f"(plan seed={plan.seed})"
        )
    if watchdog.last_trip() is not None:
        return "collective watchdog tripped on a prior step"
    from triton_distributed_tpu.runtime import health

    for ledger in health.live_ledgers():
        bad = ledger.unhealthy_peers()
        if bad:
            return (
                f"health ledger marks peer(s) {bad} unhealthy — "
                f"re-plan the mesh (topology.replan_mesh) or wait out "
                f"probation"
            )
    dp = mesh_axes_size(ctx.mesh, tuple(ctx.batch_axes))
    if engine == "ag_gemm":
        from triton_distributed_tpu.kernels.ag_gemm import auto_ag_gemm_method

        if auto_ag_gemm_method(ctx.mesh, ctx.axis, a, b, dp=dp) != \
                AGGemmMethod.PALLAS_FUSED:
            return "VMEM budget / blockability probe failed"
    elif engine == "gemm_rs":
        from triton_distributed_tpu.kernels.gemm_rs import auto_gemm_rs_method

        if auto_gemm_rs_method(ctx.mesh, ctx.axis, a, b, dp=dp) != \
                GemmRSMethod.PALLAS_FUSED:
            return "VMEM budget / blockability probe failed"
    return None


def with_fallback(fused_fn, native_fn, *, engine: str, probe=None):
    """Wrap a fused-engine entry with preflight-probe demotion to its
    XLA-native equivalent (``tools.native``): when ``probe`` returns a
    reason string the call is routed to ``native_fn`` and the demotion
    is logged ONCE per engine/reason; otherwise ``fused_fn`` runs
    untouched. The probe runs on the host before tracing — degradation
    is a dispatch decision, not an exception handler, so a demoted step
    is exactly as deterministic as a healthy one."""

    probe = probe or (lambda *a, **k: None)

    @functools.wraps(fused_fn)
    def wrapped(*args, **kwargs):
        reason = probe(*args, **kwargs)
        if reason:
            _log_demotion_once(engine, reason)
            return native_fn(*args, **kwargs)
        return fused_fn(*args, **kwargs)

    wrapped.__wrapped_engine__ = engine
    return wrapped


def _native_ag_gemm(a, b, ctx: OverlapContext):
    from triton_distributed_tpu.tools.native import xla_ag_gemm

    return xla_ag_gemm(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, out_dtype=ctx.out_dtype or a.dtype,
    )


def _native_gemm_rs(a, b, ctx: OverlapContext):
    from triton_distributed_tpu.tools.native import xla_gemm_rs

    return xla_gemm_rs(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, out_dtype=ctx.out_dtype or a.dtype,
    )


#: ``ag_gemm``/``gemm_rs`` with the degradation matrix applied — the
#: entries serving loops should call (models.Transformer routes through
#: these): healthy steps run the differentiable fused ops; a failed
#: preflight (unhealthy peer, prior watchdog trip, VMEM probe) demotes
#: to the XLA-native twin, logged once.
ag_gemm_safe = with_fallback(
    ag_gemm, _native_ag_gemm, engine="ag_gemm",
    probe=lambda a, b, ctx: preflight(ctx, "ag_gemm", a, b),
)
gemm_rs_safe = with_fallback(
    gemm_rs, _native_gemm_rs, engine="gemm_rs",
    probe=lambda a, b, ctx: preflight(ctx, "gemm_rs", a, b),
)
