"""Differentiable overlap ops: context-managed AG-GEMM / GEMM-RS.

Reference API surface: ``triton_dist.kernels`` exposes ``ag_gemm`` /
``gemm_rs`` plus ``create_*_context`` factories
(python/triton_dist/kernels/nvidia/__init__.py:25-40;
AllGatherGEMMTensorParallelContext allgather_gemm.py:407-490;
create_gemm_rs_context gemm_reduce_scatter.py:41-87). The reference is
inference-only (torch, no autograd through the kernels); here the ops are
differentiable, which is what makes the flagship *training* path possible:

* d(AG-GEMM): dA = GEMM-RS(dC, Bᵀ); dB = psum_dp(AG(A)ᵀ @ dC)
* d(GEMM-RS): dA = AG-GEMM(dC, Bᵀ); dB = psum_dp(Aᵀ @ AG(dC))

i.e. the backward of each overlap op's *activation gradient* is the dual
overlap op, so dA gets the same compute/communication overlap as the
forward — a property the stream-based reference design cannot express.
The weight gradients run as plain all_gather + matmul (XLA overlaps the
gather with neighbouring ops where it can, but there is no fused engine
for them yet).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels.ag_gemm import AGGemmMethod, ag_gemm as _ag_gemm_raw
from triton_distributed_tpu.kernels.gemm_rs import GemmRSMethod, gemm_rs as _gemm_rs_raw


def _dual_method(method, target_enum):
    """Map a pinned engine onto the dual op's enum (the backward of ag_gemm
    is a gemm_rs and vice versa; the enums share member names). None stays
    None (auto-select)."""
    if method is None:
        return None
    return target_enum[method.name]


@dataclass(frozen=True)
class OverlapContext:
    """Shared context for the TP overlap ops (≡ the reference's
    per-op *Context dataclasses, which own symmetric workspaces/streams;
    on TPU the state that must persist is just mesh/axis/method/ids)."""

    mesh: Mesh
    axis: str = "x"
    batch_axes: tuple = ()
    method: object = None          # AGGemmMethod / GemmRSMethod / None=auto
    out_dtype: object = None
    collective_id: int = 8

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]


def create_ag_gemm_context(mesh, axis="x", **kw) -> OverlapContext:
    """≡ reference create_ag_gemm_context (allgather_gemm.py:490-537)."""
    return OverlapContext(mesh=mesh, axis=axis, **kw)


def create_gemm_rs_context(mesh, axis="x", **kw) -> OverlapContext:
    """≡ reference create_gemm_rs_context (gemm_reduce_scatter.py:41-87)."""
    kw.setdefault("collective_id", 9)
    return OverlapContext(mesh=mesh, axis=axis, **kw)


def _psum_if(x, axes):
    return jax.lax.psum(x, axes) if axes else x


@functools.lru_cache(maxsize=256)
def _build_ag_wgrad(mesh, axis, batch_axes):
    """dB for ag_gemm: psum_dp( AG(A)ᵀ @ dC ) — weight grads reduce over
    the data-parallel axes, activations gather over the TP axis."""
    ba = tuple(batch_axes)

    def body(a_loc, g_loc):
        a_full = jax.lax.all_gather(a_loc, axis, tiled=True)
        db = jnp.dot(
            a_full.T.astype(jnp.float32), g_loc.astype(jnp.float32)
        )
        return _psum_if(db, ba)

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ba + (axis,) if ba else axis, None), P(ba if ba else None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _build_rs_wgrad(mesh, axis, batch_axes):
    """dB for gemm_rs: psum_dp( Aᵀ @ AG(dC) )."""
    ba = tuple(batch_axes)

    def body(a_loc, g_loc):
        g_full = jax.lax.all_gather(g_loc, axis, tiled=True)
        db = jnp.dot(
            a_loc.T.astype(jnp.float32), g_full.astype(jnp.float32)
        )
        return _psum_if(db, ba)

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ba if ba else None, axis), P(ba + (axis,) if ba else axis, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )
    return jax.jit(fn)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def ag_gemm(a, b, ctx: OverlapContext):
    """Differentiable AllGather(A) @ B (column-parallel / SP layout).

    ``a``: (M, K) rows sharded (*batch_axes, axis); ``b``: (K, N) cols
    sharded ``axis``. Returns (M, N) rows batch-sharded, cols axis-sharded.
    """
    return _ag_gemm_raw(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=ctx.method,
        out_dtype=ctx.out_dtype, collective_id=ctx.collective_id,
    )


def _ag_gemm_fwd(a, b, ctx):
    return ag_gemm(a, b, ctx), (a, b)


def _ag_gemm_bwd(ctx, res, g):
    a, b = res
    # dA: the dual overlap op — GEMM(dC, Bᵀ) fused with ReduceScatter.
    da = _gemm_rs_raw(
        g, b.T, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=_dual_method(ctx.method, GemmRSMethod),
        out_dtype=a.dtype, collective_id=ctx.collective_id + 1,
    )
    db = _build_ag_wgrad(ctx.mesh, ctx.axis, tuple(ctx.batch_axes))(a, g)
    return da, db.astype(b.dtype)


ag_gemm.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def gemm_rs(a, b, ctx: OverlapContext):
    """Differentiable (A @ B) → ReduceScatter (row-parallel / SP layout).

    ``a``: (M, K) rows batch-sharded, cols sharded ``axis``; ``b``: (K, N)
    rows sharded ``axis``. Returns (M, N) rows sharded (*batch_axes, axis).
    """
    return _gemm_rs_raw(
        a, b, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=ctx.method,
        out_dtype=ctx.out_dtype, collective_id=ctx.collective_id,
    )


def _gemm_rs_fwd(a, b, ctx):
    return gemm_rs(a, b, ctx), (a, b)


def _gemm_rs_bwd(ctx, res, g):
    a, b = res
    # dA: the dual overlap op — AllGather(dC) fused with GEMM(·, Bᵀ).
    da = _ag_gemm_raw(
        g, b.T, ctx.mesh, ctx.axis,
        batch_axes=ctx.batch_axes, method=_dual_method(ctx.method, AGGemmMethod),
        out_dtype=a.dtype, collective_id=ctx.collective_id + 1,
    )
    db = _build_rs_wgrad(ctx.mesh, ctx.axis, tuple(ctx.batch_axes))(a, g)
    return da, db.astype(b.dtype)


gemm_rs.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)
