"""Expert-parallel MoE MLP op: AllToAll dispatch → grouped GEMMs → combine.

Reference: the EP layer ``EPAll2AllLayer`` (python/triton_dist/layers/
nvidia/ep_a2a_layer.py:40-240 — preprocess splits/indices → dispatch →
caller's expert compute → combine) over the low-latency AllToAll
(low_latency_all_to_all.py) and the grouped GEMMs of
allgather_group_gemm.py:420 / moe_reduce_rs.py:362; routing ≡
select_experts (moe_reduce_rs.py:180).

TPU re-design: one ``shard_map`` body does route → expert-sort →
dispatch (padded-slot a2a) → local grouped GEMM MLP over the owned
experts → return a2a → weighted combine. Two transports:

* ``transport="pallas"``: the in-kernel remote-DMA a2a
  (kernels/all_to_all.all_to_all_device) — the low-latency inference
  path.
* ``transport="xla"``: ``lax.all_to_all`` — differentiable end-to-end
  (sort/gather/scatter/topk-softmax all have transpose rules), which is
  what makes EP *training* possible; the reference is inference-only.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.kernels.all_to_all import all_to_all_device
from triton_distributed_tpu.kernels.group_gemm import grouped_matmul, padded_splits


@dataclass(frozen=True)
class EPMoEContext:
    """Static geometry of the EP MoE layer (≡ EPAll2AllLayer's ctor state
    + AllToAllContext). Experts are sharded over ``axis``: rank r owns
    experts [r*epr, (r+1)*epr)."""

    mesh: Mesh
    axis: str
    num_experts: int
    topk: int
    max_m: int                      # per-peer token-slot capacity
    hidden: int
    dtype: jnp.dtype = jnp.bfloat16
    activation: str = "silu"        # silu | gelu | none
    transport: str = "pallas"       # pallas | xla
    block_m: int = 128
    use_pallas_gemm: bool = True
    collective_id: int = 10
    batch_axes: tuple = ()          # extra (DP) axes sharding token rows
    # Hierarchical (multi-slice) EP: experts span (dcn_axis × axis) and
    # the exchange decomposes into a same-local-rank DCN rail leg +
    # intra-slice ICI leg (≡ ep_a2a.py:36-150's node rotation with
    # same-local-rank rail puts). None → flat single-slice exchange.
    dcn_axis: str | None = None
    # Quantized token transport ("fp8" | "int8"): tokens ride the a2a at
    # 1 byte/elem with per-token scales packed in-slot (≡ the reference's
    # headline fp8 WITH_SCALE dispatch). Pallas transport only — the XLA
    # transport is the differentiable path and stays full-precision.
    quant: str | None = None

    @property
    def n(self) -> int:
        """Total EP ranks (dcn × local when hierarchical)."""
        n = self.mesh.shape[self.axis]
        if self.dcn_axis is not None:
            n *= self.mesh.shape[self.dcn_axis]
        return n

    @property
    def epl(self) -> int:
        """EP ranks per slice (the ICI leg width)."""
        return self.mesh.shape[self.axis]

    @property
    def dcn(self) -> int:
        """Number of slices on the DCN leg (1 when flat)."""
        return self.mesh.shape[self.dcn_axis] if self.dcn_axis else 1

    @property
    def ep_axes(self) -> tuple:
        """Mesh axes the experts are sharded over, DCN-major — global EP
        rank g = slice·epl + local matches P(ep_axes) dim-0 sharding."""
        return (self.dcn_axis, self.axis) if self.dcn_axis else (self.axis,)

    @property
    def experts_per_rank(self) -> int:
        return self.num_experts // self.n

    @property
    def a2a(self) -> ma.MoEAllToAllContext:
        return ma.create_all_to_all_context(
            self.mesh, self.axis, max_m=self.max_m, hidden=self.hidden,
            experts_per_rank=self.experts_per_rank, dtype=self.dtype,
            collective_id=self.collective_id, num_ranks=self.n,
            quant=self.quant,
        )


def create_ep_moe_context(
    mesh, axis, *, num_experts, topk, max_m, hidden, **kw
) -> EPMoEContext:
    ctx = EPMoEContext(
        mesh=mesh, axis=axis, num_experts=num_experts, topk=topk,
        max_m=max_m, hidden=hidden, **kw,
    )
    assert num_experts % ctx.n == 0, f"{num_experts} experts over {ctx.n} ranks"
    ctx.a2a  # fail fast on bad quant/hidden geometry, not at trace time
    if ctx.quant is not None and ctx.transport != "pallas":
        raise ValueError(
            "quantized transport rides the Pallas slot payload; the XLA "
            "transport is the differentiable full-precision path"
        )
    if ctx.transport == "pallas":
        # Pallas remote DMA cannot cross DCN: a multi-slice EP axis must
        # be declared as dcn_axis so the exchange takes the hierarchical
        # rail path (≡ the reference's CommScope INTER_NODE dispatch).
        from triton_distributed_tpu.runtime import is_dcn_axis

        if ctx.dcn_axis is None and is_dcn_axis(mesh, axis):
            raise ValueError(
                f"EP axis {axis!r} crosses DCN; pass dcn_axis= for the "
                "hierarchical exchange or transport='xla'"
            )
        if ctx.dcn_axis is not None and is_dcn_axis(mesh, ctx.axis):
            raise ValueError(
                f"intra-slice EP axis {ctx.axis!r} itself crosses DCN — "
                "swap the axes (dcn_axis must be the cross-slice one)"
            )
    return ctx


def _act(name: str, x):
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu":
        return jax.nn.gelu(x)
    return x


def _a2a(ctx: EPMoEContext, x):
    """Transpose the leading (n, ...) slot dim across EP ranks.

    Flat: one exchange over ``ctx.axis``. Hierarchical (``dcn_axis``
    set): a DCN rail leg — ``lax.all_to_all`` over the slice axis, which
    by mesh construction only connects devices with the SAME local rank
    (the reference's same-local-rank put, ep_a2a.py:70-78) — followed by
    an intra-slice ICI leg (Pallas remote-DMA a2a or lax). Both legs are
    self-inverse, so dispatch and combine use the same function.
    """
    if ctx.dcn_axis is None:
        if ctx.transport == "pallas":
            flat = x.reshape(ctx.n * x.shape[1], -1)
            out = all_to_all_device(
                flat, ctx.n, ctx.axis, ctx.mesh.axis_names,
                collective_id=ctx.collective_id,
            )
            return out.reshape(x.shape)
        return jax.lax.all_to_all(x, ctx.axis, 0, 0, tiled=False)

    dcn, epl = ctx.dcn, ctx.epl
    rest = x.shape[1:]
    y = x.reshape(dcn, epl, *rest)
    # DCN rail leg: slots for target slice d ride to (d, my_local).
    y = jax.lax.all_to_all(y, ctx.dcn_axis, 0, 0, tiled=False)
    y = jnp.swapaxes(y, 0, 1)                       # (local_dst, slice_src, ...)
    # ICI leg: deliver each slot to its final local rank within my slice.
    if ctx.transport == "pallas":
        flat = y.reshape(epl * dcn * rest[0], -1)
        out = all_to_all_device(
            flat, epl, ctx.axis, ctx.mesh.axis_names,
            collective_id=ctx.collective_id,
        )
        y = out.reshape(epl, dcn, *rest)            # (local_src, slice_src, ...)
    else:
        y = jax.lax.all_to_all(y, ctx.axis, 0, 0, tiled=False)
    # back to global-rank-major (slice·epl + local)
    return jnp.swapaxes(y, 0, 1).reshape(ctx.n, *rest)


def _dispatch(ctx: EPMoEContext, x_sorted, splits):
    """Stage + exchange → ((n, max_m, H) tokens, clamped (n, epr) splits).

    Pallas: one bitcast int32 payload per peer (inference fast path).
    XLA: tokens and splits ride two ``lax.all_to_all`` calls so the
    float tokens never cross a gradient-opaque bitcast (training path).
    """
    a2a = ctx.a2a
    toks, spl = ma.dispatch_stage(a2a, x_sorted, splits)
    if ctx.transport == "pallas":
        recv = _a2a(ctx, ma.pack_slots(a2a, toks, spl).reshape(
            ctx.n, a2a.slot_rows, a2a.ints_per_row))
        return ma.recv_tokens_view(a2a, recv)
    rtoks = _a2a(ctx, toks)
    rspl = _a2a(ctx, spl[:, None, :])[:, 0, :]
    return rtoks, ma.clamp_recv_splits(a2a, rspl)


def _combine(ctx: EPMoEContext, y_slots, splits, total):
    """Return-leg exchange + unstage → (total, H) in sorted order."""
    a2a = ctx.a2a
    if ctx.transport == "pallas":
        comb = _a2a(ctx, ma.combine_stage(a2a, y_slots).reshape(
            ctx.n, a2a.slot_rows, a2a.ints_per_row))
        toks = ma.combine_unpack(a2a, comb)
    else:
        toks = _a2a(ctx, y_slots)
    return ma.combine_unstage(a2a, toks, splits, total)


def _expert_mlp(ctx: EPMoEContext, rows, eid, valid, w_up, w_down):
    """Grouped MLP over this rank's experts.

    rows: (R, H) received tokens; eid: (R,) local expert ids; valid: (R,)
    bool. w_up: (epr, H, F); w_down: (epr, F, H). Invalid rows are zero
    and sorted into a trailing dummy group, so they contribute zeros.
    """
    epr = ctx.experts_per_rank
    r = rows.shape[0]
    # sort received rows by local expert, invalid rows to a dummy tail
    # group — the align-block trick over receive-side data
    ids = jnp.where(valid, eid, epr).astype(jnp.int32)[:, None]
    sti, be, counts = mu.moe_align_block_size(ids, epr + 1, ctx.block_m)
    cap = sti.shape[0]
    safe = jnp.clip(sti, 0, r - 1)
    ok = (sti < r) & valid[safe]
    xs = jnp.where(ok[:, None], rows[safe], 0).astype(ctx.dtype)
    # dummy blocks (be == epr) read the LAST expert's weights; their rows
    # are zero so the product is zero regardless
    be_w = jnp.clip(be, 0, epr - 1)

    if ctx.use_pallas_gemm:
        h = grouped_matmul(xs, w_up, be_w, block_m=ctx.block_m)
        h = _act(ctx.activation, h).astype(ctx.dtype)
        y = grouped_matmul(h, w_down, be_w, block_m=ctx.block_m)
    else:
        # aligned group sizes; the dummy group and tail slack are zero
        # rows — fold them into the last real expert
        gs_all = padded_splits(counts, ctx.block_m, cap)
        gs = gs_all[:epr].at[-1].add(gs_all[epr])
        h = jax.lax.ragged_dot(xs, w_up, gs)
        h = _act(ctx.activation, h).astype(ctx.dtype)
        y = jax.lax.ragged_dot(h, w_down, gs)
    y = jnp.where(ok[:, None], y, 0)
    # scatter back to received-row order
    out = jnp.zeros((r + 1, y.shape[-1]), ctx.dtype)
    dest = jnp.where(sti < r, sti, r)
    return out.at[dest].set(y)[:r]


def ep_moe_device(x, logits, w_up, w_down, ctx: EPMoEContext):
    """Per-device EP MoE body — callable inside any shard_map.

    x: (M, H) this rank's tokens; logits: (M, E); w_up: (epr, H, F),
    w_down: (epr, F, H) — this rank's experts. Returns (M, H).
    """
    m = x.shape[0]
    total = m * ctx.topk
    weights, ids = mu.select_experts(logits, ctx.topk)
    flat = ids.reshape(-1)
    order = jnp.argsort(flat, stable=True).astype(jnp.int32)
    splits = jnp.zeros((ctx.num_experts,), jnp.int32).at[flat].add(1)
    x_sorted = x[order // ctx.topk].astype(ctx.dtype)

    # dispatch: tokens to the ranks owning their experts
    toks, rspl = _dispatch(ctx, x_sorted, splits)      # (n,max_m,H),(n,epr)
    rows = toks.reshape(ctx.n * ctx.max_m, ctx.hidden)
    pos = jnp.arange(ctx.max_m, dtype=jnp.int32)
    cum = jnp.cumsum(rspl, axis=1)                     # (n, epr)
    eid = jax.vmap(lambda c: jnp.searchsorted(c, pos, side="right"))(cum)
    eid = jnp.clip(eid, 0, ctx.experts_per_rank - 1).reshape(-1)
    valid = (pos[None, :] < cum[:, -1][:, None]).reshape(-1)

    y = _expert_mlp(ctx, rows, eid, valid, w_up, w_down)

    # combine: processed tokens back to their owners
    y_sorted = _combine(
        ctx, y.reshape(ctx.n, ctx.max_m, ctx.hidden), splits, total
    )
    w_flat = weights.reshape(-1)[order].astype(jnp.float32)
    out = jnp.zeros((m, ctx.hidden), jnp.float32)
    out = out.at[order // ctx.topk].add(
        y_sorted.astype(jnp.float32) * w_flat[:, None]
    )
    return out.astype(x.dtype)


@functools.lru_cache(maxsize=64)
def _build_ep_moe(ctx: EPMoEContext, ikey: tuple = ()):
    # ikey: config.interp_key() — chaos/race knobs are baked in at trace
    # time, so they must participate in the cache identity (like every
    # other kernel builder; del keeps the signature honest about usage).
    del ikey
    rows = P(tuple(ctx.batch_axes) + ctx.ep_axes)
    experts = P(ctx.ep_axes)
    fn = jax.shard_map(
        functools.partial(ep_moe_device, ctx=ctx),
        mesh=ctx.mesh,
        in_specs=(rows, rows, experts, experts),
        out_specs=rows,
        check_vma=False,
    )
    return jax.jit(fn)


def ep_moe(x, logits, w_up, w_down, ctx: EPMoEContext):
    """Host entry: EP MoE MLP on ``ctx.mesh``.

    Global shapes: x (M, H) and logits (M, E) token-sharded over
    ``ctx.axis``; w_up (E, H, F) / w_down (E, F, H) expert-sharded over
    ``ctx.axis``. Returns (M, H) token-sharded.
    """
    from triton_distributed_tpu.config import interp_key

    return _build_ep_moe(ctx, interp_key())(x, logits, w_up, w_down)


_EP_MOE_TUNERS: OrderedDict = OrderedDict()
_EP_MOE_TUNERS_MAX = 64          # bounded like the sibling _build caches


def ep_moe_tuned(x, logits, w_up, w_down, ctx: EPMoEContext,
                 candidates: tuple = (64, 128, 256)):
    """``ep_moe`` with ``block_m`` autotuned per input shape.

    The L6→L4 integration the reference gets from wrapping kernels in
    ``contextual_autotune`` (autotuner.py:97): the whole thunk is
    benchmarked per block size (alignment capacity changes with it, so
    the tuning unit must be the op, not the inner GEMM), the winner is
    cached per shape, and on multi-process meshes the MAX-consensus
    keeps every process on the same config.
    """
    from triton_distributed_tpu.tune import ContextualAutoTuner  # cycle: tune→ops is none, but keep ops importable without tune at module load

    key = (ctx, tuple(candidates))
    tuner = _EP_MOE_TUNERS.get(key)
    if tuner is None:
        def run(x, logits, up, down, *, block_m):
            return ep_moe(x, logits, up, down, replace(ctx, block_m=block_m))

        # ctx is part of the tuner identity: the persistent winner store
        # keys on (name, arg shapes), and two contexts with identical
        # token shapes but different transport/quant/geometry must not
        # share winners
        ctx_tag = (
            f"{dict(ctx.mesh.shape)}|{ctx.axis}|{ctx.dcn_axis}|"
            f"E{ctx.num_experts}k{ctx.topk}m{ctx.max_m}|{ctx.transport}|"
            f"{ctx.quant}|{jnp.dtype(ctx.dtype).name}"
        )
        tuner = ContextualAutoTuner(
            run, [{"block_m": b} for b in candidates],
            name=f"ep_moe[{ctx_tag}]",
        )
        _EP_MOE_TUNERS[key] = tuner
        while len(_EP_MOE_TUNERS) > _EP_MOE_TUNERS_MAX:
            _EP_MOE_TUNERS.popitem(last=False)
    else:
        _EP_MOE_TUNERS.move_to_end(key)
    return tuner(x, logits, w_up, w_down)
