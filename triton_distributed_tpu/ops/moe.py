"""Expert-parallel MoE MLP op: AllToAll dispatch → grouped GEMMs → combine.

Reference: the EP layer ``EPAll2AllLayer`` (python/triton_dist/layers/
nvidia/ep_a2a_layer.py:40-240 — preprocess splits/indices → dispatch →
caller's expert compute → combine) over the low-latency AllToAll
(low_latency_all_to_all.py) and the grouped GEMMs of
allgather_group_gemm.py:420 / moe_reduce_rs.py:362; routing ≡
select_experts (moe_reduce_rs.py:180).

TPU re-design: one ``shard_map`` body does route → expert-sort →
dispatch → local grouped GEMM MLP over the owned experts → return a2a →
weighted combine. Three transports:

* ``transport="fused"`` (flat-mesh default): in-kernel per-peer window
  DMAs straight from the aligned expert-sorted payload
  (kernels/moe_dispatch) — the low-latency inference path.
* ``transport="pallas"``: staged padded-slot in-kernel a2a
  (kernels/all_to_all.all_to_all_device) — the hierarchical-capable
  transport (default when ``dcn_axis`` is set).
* ``transport="xla"``: ``lax.all_to_all`` — differentiable end-to-end
  (sort/gather/scatter/topk-softmax all have transpose rules), which is
  what makes EP *training* possible; the reference is inference-only.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from dataclasses import dataclass, replace

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from triton_distributed_tpu.kernels import moe_all_to_all as ma
from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.kernels.all_to_all import all_to_all_device
from triton_distributed_tpu.kernels.group_gemm import grouped_matmul, padded_splits


@dataclass(frozen=True)
class EPMoEContext:
    """Static geometry of the EP MoE layer (≡ EPAll2AllLayer's ctor state
    + AllToAllContext). Experts are sharded over ``axis``: rank r owns
    experts [r*epr, (r+1)*epr)."""

    mesh: Mesh
    axis: str
    num_experts: int
    topk: int
    # Transport capacity. Staged ("pallas"/"xla") transports read it as
    # PER-PEER slot capacity (overflow beyond it is clamped); the fused
    # transport needs TOTAL-assignment capacity (max_m ≥ M·topk, the
    # standard worst-case sizing) and degrades to the staged path with a
    # warning when sized smaller.
    max_m: int
    hidden: int
    dtype: jnp.dtype = jnp.bfloat16
    activation: str = "silu"        # silu | gelu | none
    # "fused": in-kernel per-peer window DMAs straight from the aligned
    #   expert-sorted payload — the low-latency inference path
    #   (kernels/moe_dispatch, ≡ the reference's on-device range
    #   computation, low_latency_all_to_all.py:36-80). Flat meshes only;
    #   requires max_m ≥ M·topk (the worst-case total, the standard
    #   sizing).
    # "pallas": staged padded-slot a2a (kernels/moe_all_to_all) — the
    #   hierarchical-capable in-kernel transport.
    # "xla": lax.all_to_all — differentiable end to end (training).
    # None (default): "fused" on flat meshes, "pallas" hierarchical.
    transport: str | None = None    # fused | pallas | xla
    block_m: int = 128
    use_pallas_gemm: bool = True
    # Grouped-GEMM N/K tiles (None → kernel defaults). Setting both to
    # a huge value (whole-dim) enables the WEIGHT-RESIDENT schedule:
    # each expert's full weight matrix stays in VMEM across its
    # consecutive sorted blocks, so block_m can shrink (less alignment
    # padding) without re-streaming weights per block — the decode-size
    # optimum (group_gemm.grouped_matmul docstring).
    gg_block_n: int | None = None
    gg_block_k: int | None = None
    collective_id: int = 10
    batch_axes: tuple = ()          # extra (DP) axes sharding token rows
    # Hierarchical (multi-slice) EP: experts span (dcn_axis × axis) and
    # the exchange decomposes into a same-local-rank DCN rail leg +
    # intra-slice ICI leg (≡ ep_a2a.py:36-150's node rotation with
    # same-local-rank rail puts). None → flat single-slice exchange.
    dcn_axis: str | None = None
    # Quantized token transport ("fp8" | "int8"): tokens ride the a2a at
    # 1 byte/elem with per-token scales in the wire metadata (≡ the
    # reference's headline fp8 WITH_SCALE dispatch). Carried by the
    # "fused" and "pallas" transports; the XLA transport is the
    # differentiable path and stays full-precision.
    quant: str | None = None
    # W8A8 expert GEMMs ("int8"): quantize the ACTIVATIONS per row too
    # and run the MXU's native s8×s8→s32 path (2× the bf16 rate, the
    # remaining lever once the weight-resident schedule has minimized
    # HBM reads). Requires int8 weight dicts + the Pallas GEMM; sweet
    # spot block_m=128 (the int8 rate needs ≥128-row blocks while the
    # alignment-padding tax grows with block_m — measured 292 vs 356 µs
    # per decode up-GEMM against W8A16 at bm=64, docs/PERF.md).
    act_quant: str | None = None

    @property
    def n(self) -> int:
        """Total EP ranks (dcn × local when hierarchical)."""
        n = self.mesh.shape[self.axis]
        if self.dcn_axis is not None:
            n *= self.mesh.shape[self.dcn_axis]
        return n

    @property
    def epl(self) -> int:
        """EP ranks per slice (the ICI leg width)."""
        return self.mesh.shape[self.axis]

    @property
    def dcn(self) -> int:
        """Number of slices on the DCN leg (1 when flat)."""
        return self.mesh.shape[self.dcn_axis] if self.dcn_axis else 1

    @property
    def ep_axes(self) -> tuple:
        """Mesh axes the experts are sharded over, DCN-major — global EP
        rank g = slice·epl + local matches P(ep_axes) dim-0 sharding."""
        return (self.dcn_axis, self.axis) if self.dcn_axis else (self.axis,)

    @property
    def experts_per_rank(self) -> int:
        return self.num_experts // self.n

    @property
    def a2a(self) -> ma.MoEAllToAllContext:
        return ma.create_all_to_all_context(
            self.mesh, self.axis, max_m=self.max_m, hidden=self.hidden,
            experts_per_rank=self.experts_per_rank, dtype=self.dtype,
            collective_id=self.collective_id, num_ranks=self.n,
            quant=self.quant,
        )


def create_ep_moe_context(
    mesh, axis, *, num_experts, topk, max_m, hidden, **kw
) -> EPMoEContext:
    ctx = EPMoEContext(
        mesh=mesh, axis=axis, num_experts=num_experts, topk=topk,
        max_m=max_m, hidden=hidden, **kw,
    )
    if ctx.transport is None:
        from triton_distributed_tpu.config import pallas_collectives_available

        if not pallas_collectives_available() and ctx.quant is None:
            # off-TPU without the TPU-simulation interpreter: the Pallas
            # transports cannot execute — auto-select degrades to the
            # XLA a2a (quantized payloads still require Pallas and fail
            # loudly below)
            ctx = replace(ctx, transport="xla")
        else:
            ctx = replace(
                ctx,
                transport="pallas" if ctx.dcn_axis is not None else "fused",
            )
    assert num_experts % ctx.n == 0, f"{num_experts} experts over {ctx.n} ranks"
    ctx.a2a  # fail fast on bad quant/hidden geometry, not at trace time
    if ctx.quant is not None and ctx.transport == "xla":
        raise ValueError(
            "quantized transport rides the Pallas slot payload; the XLA "
            "transport is the differentiable full-precision path"
        )
    if ctx.act_quant not in (None, "int8"):
        raise ValueError(f"act_quant must be None or 'int8', got {ctx.act_quant!r}")
    if ctx.transport == "fused" and ctx.dcn_axis is not None:
        raise ValueError(
            "the fused window-DMA transport is flat (single-slice) only; "
            "use transport='pallas' for the hierarchical exchange"
        )
    if ctx.transport in ("pallas", "fused"):
        # Pallas remote DMA cannot cross DCN: a multi-slice EP axis must
        # be declared as dcn_axis so the exchange takes the hierarchical
        # rail path (≡ the reference's CommScope INTER_NODE dispatch).
        from triton_distributed_tpu.runtime import is_dcn_axis

        if ctx.dcn_axis is None and is_dcn_axis(mesh, axis):
            raise ValueError(
                f"EP axis {axis!r} crosses DCN; pass dcn_axis= for the "
                "hierarchical exchange or transport='xla'"
            )
        if ctx.dcn_axis is not None and is_dcn_axis(mesh, ctx.axis):
            raise ValueError(
                f"intra-slice EP axis {ctx.axis!r} itself crosses DCN — "
                "swap the axes (dcn_axis must be the cross-slice one)"
            )
    return ctx


@dataclass
class EPMoEState:
    """Persistent workspaces of the BARRIER-FREE fused transport (≡ the
    reference AllToAllContext's symmetric buffers + call_count,
    low_latency_all_to_all.py:125-187). Owns the double-buffered
    receive windows for both legs and the parity counter; thread the
    returned state through successive ``ep_moe(..., state=)`` calls
    (the arrays are donated — always use the returned state).

    ``instance`` keys the compiled kernels per live state so two states
    never share physical per-parity semaphores (see
    moe_dispatch._build_chunked_a2a_ll)."""

    parity: jax.Array       # (1,) int32, replicated
    disp_tok: jax.Array     # dispatch windows, P(batch+ep) sharded
    disp_meta: jax.Array
    comb_tok: jax.Array     # combine windows
    comb_meta: jax.Array
    instance: int = 0       # static (pytree aux data)

    def as_dict(self):
        return {
            "parity": self.parity,
            "disp_tok": self.disp_tok, "disp_meta": self.disp_meta,
            "comb_tok": self.comb_tok, "comb_meta": self.comb_meta,
        }


jax.tree_util.register_dataclass(
    EPMoEState,
    data_fields=["parity", "disp_tok", "disp_meta", "comb_tok", "comb_meta"],
    meta_fields=["instance"],
)

_NEXT_LL_INSTANCE = [0]


def create_ep_moe_state(ctx: EPMoEContext, abstract: bool = False) -> EPMoEState:
    """Allocate zeroed persistent LL workspaces for ``ctx`` (fused flat
    transport only). Each call consumes TWO kernel instances (dispatch,
    combine). ``abstract=True`` returns ShapeDtypeStruct leaves instead
    of device arrays — for lowering/compiling against an unattached
    topology mesh (tests/test_aot_topology.py)."""
    import numpy as np
    from jax.sharding import NamedSharding

    from triton_distributed_tpu.kernels import moe_dispatch as md

    if ctx.transport != "fused" or ctx.dcn_axis is not None:
        raise ValueError(
            "EPMoEState rides the flat fused transport "
            f"(got transport={ctx.transport!r}, dcn_axis={ctx.dcn_axis!r})"
        )
    a2a = ctx.a2a
    (tok_shape, tok_dt), (meta_shape, meta_dt) = md.ll_workspace_shapes(a2a)
    row_axes = tuple(ctx.batch_axes) + ctx.ep_axes
    shards = int(np.prod([ctx.mesh.shape[ax] for ax in row_axes]))
    sh = NamedSharding(ctx.mesh, P(row_axes))
    rep = NamedSharding(ctx.mesh, P())

    if abstract:
        def ws(shape, dt, sharding=sh):
            return jax.ShapeDtypeStruct(shape, dt, sharding=sharding)

        tok_shape = (shards * tok_shape[0],) + tok_shape[1:]
        meta_shape = (shards * meta_shape[0],) + meta_shape[1:]
        inst = _NEXT_LL_INSTANCE[0]
        _NEXT_LL_INSTANCE[0] += 2
        return EPMoEState(
            parity=ws((1,), jnp.int32, rep),
            disp_tok=ws(tok_shape, tok_dt),
            disp_meta=ws(meta_shape, meta_dt),
            comb_tok=ws(tok_shape, tok_dt),
            comb_meta=ws(meta_shape, meta_dt),
            instance=inst,
        )

    def ws(shape, dt):
        return jax.device_put(
            jnp.zeros((shards * shape[0],) + shape[1:], dt), sh
        )

    inst = _NEXT_LL_INSTANCE[0]
    _NEXT_LL_INSTANCE[0] += 2
    return EPMoEState(
        parity=jax.device_put(jnp.zeros((1,), jnp.int32), rep),
        disp_tok=ws(tok_shape, tok_dt),
        disp_meta=ws(meta_shape, meta_dt),
        comb_tok=ws(tok_shape, tok_dt),
        comb_meta=ws(meta_shape, meta_dt),
        instance=inst,
    )


def _act(name: str, x):
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu":
        return jax.nn.gelu(x)
    return x


def _a2a(ctx: EPMoEContext, x):
    """Transpose the leading (n, ...) slot dim across EP ranks — the
    FLAT exchange over ``ctx.axis`` (hierarchical meshes never reach
    here: ``_ep_moe_hier_device`` decomposes into a dedup'd DCN rail +
    a flat intra-slice exchange before any slot staging happens)."""
    if ctx.transport == "pallas":
        flat = x.reshape(ctx.n * x.shape[1], -1)
        out = all_to_all_device(
            flat, ctx.n, ctx.axis, ctx.mesh.axis_names,
            collective_id=ctx.collective_id,
        )
        return out.reshape(x.shape)
    return jax.lax.all_to_all(x, ctx.axis, 0, 0, tiled=False)


def _dispatch(ctx: EPMoEContext, x_sorted, splits):
    """Stage + exchange → ((n, max_m, H) tokens, clamped (n, epr) splits).

    Pallas: one bitcast int32 payload per peer (inference fast path).
    XLA: tokens and splits ride two ``lax.all_to_all`` calls so the
    float tokens never cross a gradient-opaque bitcast (training path).
    """
    a2a = ctx.a2a
    toks, spl = ma.dispatch_stage(a2a, x_sorted, splits)
    if ctx.transport == "pallas":
        recv = _a2a(ctx, ma.pack_slots(a2a, toks, spl).reshape(
            ctx.n, a2a.slot_rows, a2a.ints_per_row))
        return ma.recv_tokens_view(a2a, recv)
    rtoks = _a2a(ctx, toks)
    rspl = _a2a(ctx, spl[:, None, :])[:, 0, :]
    return rtoks, ma.clamp_recv_splits(a2a, rspl)


def _combine(ctx: EPMoEContext, y_slots, splits, total):
    """Return-leg exchange + unstage → (total, H) in sorted order."""
    a2a = ctx.a2a
    if ctx.transport == "pallas":
        comb = _a2a(ctx, ma.combine_stage(a2a, y_slots).reshape(
            ctx.n, a2a.slot_rows, a2a.ints_per_row))
        toks = ma.combine_unpack(a2a, comb)
    else:
        toks = _a2a(ctx, y_slots)
    return ma.combine_unstage(a2a, toks, splits, total)


def _expert_mlp(ctx: EPMoEContext, rows, eid, valid, w_up, w_down):
    """Grouped MLP over this rank's experts.

    rows: (R, H) received tokens; eid: (R,) local expert ids; valid: (R,)
    bool. w_up: (epr, H, F); w_down: (epr, F, H). Invalid rows are zero
    and sorted into a trailing dummy group, so they contribute zeros.

    Either weight may instead be a WEIGHT-QUANTIZED dict
    ``{"q": (epr, K, N) int8/fp8, "scale": (epr, N) f32}`` (from
    group_gemm.quantize_grouped_weights): the Pallas path folds the
    scale into the GEMM epilogue, halving the weight HBM reads that
    dominate decode-size grouped GEMMs; the XLA twin widens first.
    """
    epr = ctx.experts_per_rank
    r = rows.shape[0]
    # sort received rows by local expert, invalid rows to a dummy tail
    # group — the align-block trick over receive-side data
    ids = jnp.where(valid, eid, epr).astype(jnp.int32)[:, None]
    sti, be, counts = mu.moe_align_block_size(ids, epr + 1, ctx.block_m)
    cap = sti.shape[0]
    safe = jnp.clip(sti, 0, r - 1)
    ok = (sti < r) & valid[safe]
    xs = jnp.where(ok[:, None], rows[safe], 0).astype(ctx.dtype)
    # dummy blocks (be == epr) read the LAST expert's weights; their rows
    # are zero so the product is zero regardless
    be_w = jnp.clip(be, 0, epr - 1)

    if ctx.use_pallas_gemm:
        gg_kw = {}
        if ctx.gg_block_n is not None:
            gg_kw["block_n"] = ctx.gg_block_n
        if ctx.gg_block_k is not None:
            gg_kw["block_k"] = ctx.gg_block_k
        if gg_kw:
            from triton_distributed_tpu.config import fused_vmem_budget

            gg_kw["vmem_limit_bytes"] = fused_vmem_budget()

        def gg(inp, w):
            if isinstance(w, dict):
                return grouped_matmul(
                    inp, w["q"], be_w, w_scale=w["scale"],
                    block_m=ctx.block_m, **gg_kw,
                )
            return grouped_matmul(inp, w, be_w, block_m=ctx.block_m, **gg_kw)

        if (
            ctx.act_quant == "int8"
            and isinstance(w_up, dict) and isinstance(w_down, dict)
            and w_up["q"].dtype == jnp.int8 and w_down["q"].dtype == jnp.int8
        ):
            # W8A8: per-row int8 activations into the s8×s8 MXU path
            # (2× rate); the hidden activation re-quantizes after the
            # nonlinearity (its own per-row scale — the only extra
            # quantization step beyond what the int8 wire already did)
            from triton_distributed_tpu.kernels.group_gemm import (
                quantize_act_rows,
            )

            def gg8(q_in, s_in, w):
                return grouped_matmul(
                    q_in, w["q"], be_w, w_scale=w["scale"], x_scale=s_in,
                    block_m=ctx.block_m, out_dtype=ctx.dtype, **gg_kw,
                )

            xq, xsc = quantize_act_rows(xs)
            h = _act(ctx.activation, gg8(xq, xsc, w_up))
            hq, hsc = quantize_act_rows(h)
            y = gg8(hq, hsc, w_down)
        else:
            h = gg(xs, w_up)
            h = _act(ctx.activation, h).astype(ctx.dtype)
            y = gg(h, w_down)
    else:
        from triton_distributed_tpu.kernels.group_gemm import (
            dequantize_grouped_weights,
        )

        if isinstance(w_up, dict):
            w_up = dequantize_grouped_weights(
                w_up["q"], w_up["scale"], ctx.dtype
            )
        if isinstance(w_down, dict):
            w_down = dequantize_grouped_weights(
                w_down["q"], w_down["scale"], ctx.dtype
            )
        # aligned group sizes; the dummy group and tail slack are zero
        # rows — fold them into the last real expert
        gs_all = padded_splits(counts, ctx.block_m, cap)
        gs = gs_all[:epr].at[-1].add(gs_all[epr])
        h = jax.lax.ragged_dot(xs, w_up, gs)
        h = _act(ctx.activation, h).astype(ctx.dtype)
        y = jax.lax.ragged_dot(h, w_down, gs)
    # no post-GEMM re-masking: invalid/slack rows entered the GEMMs as
    # exact zeros (xs above), so their outputs are exact zeros — the
    # old (cap, H) `where` pass was a full ~23 MB r+w of dead HBM
    # bandwidth at serving shapes.
    # un-sort via inverse-permutation GATHER: every received row index
    # appears exactly once in sti (it is a sort of all r rows), so the
    # inverse is total — scatter only the (cap,) int32 iota (trivial;
    # padding entries drop out of bounds), then move the big array with
    # one gather instead of scattering (cap, H) rows.
    inv = jnp.zeros((r,), jnp.int32).at[sti].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop"
    )
    return y[inv]


def _slot_tables(ctx: EPMoEContext, rspl, slot_m: int, shift=None):
    """(eid, valid) for (n, slot_m) receive slots from clamped counts.
    ``shift`` (n,): per-slot row offset of the segment inside the window
    (fused transport under extreme skew; None → 0)."""
    pos = jnp.arange(slot_m, dtype=jnp.int32)
    cum = jnp.cumsum(rspl, axis=1)                     # (n, epr)
    rel = pos[None, :] - (
        jnp.zeros((rspl.shape[0], 1), jnp.int32) if shift is None
        else shift[:, None]
    )
    eid = jax.vmap(
        lambda c, r: jnp.searchsorted(c, r, side="right")
    )(cum, rel)
    eid = jnp.clip(eid, 0, ctx.experts_per_rank - 1).reshape(-1)
    valid = ((rel >= 0) & (rel < cum[:, -1][:, None])).reshape(-1)
    return eid, valid


def _ep_assignments_device(ctx: EPMoEContext, x, flat_e, w_flat, out_rows,
                           w_up, w_down, state=None, instance=0):
    """Dispatch pre-routed assignments → grouped MLP → combine →
    weighted scatter, on a FLAT exchange over ``ctx.axis``.

    x: (R, H) token rows; flat_e: (T,) exchange-local expert id per
    assignment (T = R·topk; the SENTINEL ``ctx.num_experts`` marks a
    masked assignment — sorted to the tail, never shipped); w_flat:
    (T,) f32 combine weights, exactly 0 for masked assignments.
    Returns (out_rows, H) f32 weighted sums (out_rows == R) — plus the
    updated workspace dict when ``state`` is given (the barrier-free LL
    transport; fused only).
    """
    total = flat_e.shape[0]
    new_state = None
    order = jnp.argsort(flat_e, stable=True).astype(jnp.int32)
    valid_a = flat_e < ctx.num_experts
    n_valid = jnp.sum(valid_a.astype(jnp.int32))
    splits = jnp.zeros((ctx.num_experts,), jnp.int32).at[
        jnp.clip(flat_e, 0, ctx.num_experts - 1)
    ].add(valid_a.astype(jnp.int32))

    transport = ctx.transport
    if transport == "fused" and ctx.max_m < total:
        if state is not None:
            raise ValueError(
                f"ep_moe LL state: max_m={ctx.max_m} < M·topk={total} — "
                "the fused transport needs full-assignment capacity and "
                "the persistent workspaces are sized by it"
            )
        # the fused aligned payload must hold EVERY assignment; a
        # per-peer-capacity max_m (< M·topk — the documented sizing the
        # staged transport clamps against) degrades to the padded-slot
        # path instead of failing, preserving the old overflow semantics
        from triton_distributed_tpu.kernels.ag_gemm import _warn_once

        _warn_once(
            ("ep_moe", "fused_cap", ctx.max_m, total),
            f"ep_moe: max_m={ctx.max_m} < M·topk={total}; the fused "
            "window transport needs full-assignment capacity — using "
            "the padded-slot transport (overflow-clamping) instead",
        )
        transport = "pallas"
        ctx = replace(ctx, transport="pallas")

    if transport == "fused":
        from triton_distributed_tpu.kernels import moe_dispatch as md

        a2a = ctx.a2a
        # single staging pass: gather straight from x into the aligned
        # per-peer segments (no x_sorted materialization, no slot
        # inflation — the reference's on-device range computation)
        counts, offs, offs_al, sendk = md.send_plan(a2a, splits)
        peer, dest = md.assignment_dest(a2a, flat_e[order], offs, offs_al)
        payload, scales = md.stage_aligned(
            a2a, x, order // ctx.topk, dest, n_valid
        )
        meta = md.meta_payload(a2a, splits, scales, offs_al, sendk)
        if state is None:
            recv_tok, recv_meta = md.dispatch_device(
                a2a, payload, offs_al, sendk, meta
            )
        else:
            dtok, dmeta = md.dispatch_ll_device(
                a2a, payload, offs_al, sendk, meta,
                state["parity"], state["disp_tok"], state["disp_meta"],
                instance,
            )
            recv_tok, recv_meta = md.ll_window(a2a, dtok, dmeta,
                                               state["parity"])
        toks, rspl = md.recv_view(a2a, recv_tok, recv_meta)

        slot_m = md.slot_pad(a2a)
        eid, valid = _slot_tables(ctx, rspl, slot_m)
        y = _expert_mlp(
            ctx, toks.reshape(ctx.n * slot_m, ctx.hidden), eid, valid,
            w_up, w_down,
        )
        # return leg: slot-regular — the same chunked kernel with static
        # slot offsets carries back exactly the received row ranges
        y_tok, y_meta = md.stage_return(
            a2a, y.reshape(ctx.n, slot_m, ctx.hidden)
        )
        retk = -(-jnp.sum(rspl, axis=1) // md.chunk_rows(a2a))
        if state is None:
            comb_tok, comb_meta = md.combine_device(
                a2a, y_tok, y_meta, retk, sendk
            )
        else:
            ctok, cmeta = md.combine_ll_device(
                a2a, y_tok, y_meta, retk, sendk,
                state["parity"], state["comb_tok"], state["comb_meta"],
                instance + 1,
            )
            comb_tok, comb_meta = md.ll_window(a2a, ctok, cmeta,
                                               state["parity"])
            new_state = {
                "parity": (state["parity"] + 1) % 2,
                "disp_tok": dtok, "disp_meta": dmeta,
                "comb_tok": ctok, "comb_meta": cmeta,
            }
        y_sorted = md.combine_view(
            a2a, comb_tok, comb_meta, peer, dest, offs_al, n_valid
        )
    else:
        x_sorted = x[order // ctx.topk].astype(ctx.dtype)
        # dispatch: tokens to the ranks owning their experts
        toks, rspl = _dispatch(ctx, x_sorted, splits)  # (n,max_m,H),(n,epr)
        eid, valid = _slot_tables(ctx, rspl, ctx.max_m)
        y = _expert_mlp(
            ctx, toks.reshape(ctx.n * ctx.max_m, ctx.hidden), eid, valid,
            w_up, w_down,
        )
        # combine: processed tokens back to their owners
        y_sorted = _combine(
            ctx, y.reshape(ctx.n, ctx.max_m, ctx.hidden), splits, total
        )

    # back to assignment order via inverse-permutation GATHER (scatter
    # only the (T,) iota; total-coverage since ``order`` is a
    # permutation), then reduce the topk groups with a segmented sum —
    # assignment t belongs to token t//topk, so the (T, H) array IS
    # (out_rows, topk, H) row-major. One gather + one reduction pass
    # instead of a full-width f32 select pass + an f32 scatter-add.
    inv_order = jnp.zeros((total,), jnp.int32).at[order].set(
        jnp.arange(total, dtype=jnp.int32)
    )
    y_orig = y_sorted[inv_order]                   # (T, H) assignment order
    # masked assignments carry weight exactly 0, but their y rows may be
    # garbage (untransported window slack) — zero them before the MAC so
    # a stray inf/nan cannot poison the sum. Under debug_checksum the
    # poison NaNs ride rows with nonzero weight, so they stay loud.
    y_use = jnp.where(
        (w_flat != 0)[:, None],
        y_orig.astype(jnp.float32) * w_flat[:, None],
        0.0,
    )
    out = y_use.reshape(out_rows, ctx.topk, ctx.hidden).sum(axis=1)
    return (out, new_state) if state is not None else out


def _rail_stage(ctx: EPMoEContext, x, ids, weights):
    """Dedup rail staging: ONE row per unique (token, target-slice) pair.

    Returns (tok_slot (dcn, M, H), ids_slot (dcn, M, topk) [-1 pad],
    w_slot (dcn, M, topk) [0 pad], hit (M, dcn), u_counts (dcn,)).
    Capacity is M rows per slice — DCN payload scales with unique
    tokens, never with topk duplicates (≡ the reference's once-per-node
    put + local scatter, ep_a2a.py:74-80, :120-150)."""
    m = x.shape[0]
    slice_experts = ctx.epl * ctx.experts_per_rank
    e_slice = ids // slice_experts                       # (m, topk)
    d_idx = jnp.arange(ctx.dcn, dtype=jnp.int32)
    hit = (e_slice[:, :, None] == d_idx[None, None, :]).any(axis=1)  # (m,dcn)
    u_counts = hit.sum(axis=0).astype(jnp.int32)
    tok_of_slot = jnp.argsort(
        jnp.where(hit.T, jnp.arange(m, dtype=jnp.int32)[None, :], m),
        axis=1, stable=True,
    ).astype(jnp.int32)                                  # (dcn, m)
    valid_u = jnp.arange(m, dtype=jnp.int32)[None, :] < u_counts[:, None]
    safe = jnp.clip(tok_of_slot, 0, m - 1)
    tok_slot = jnp.where(valid_u[..., None], x[safe], 0).astype(ctx.dtype)
    ids_slot = jnp.where(valid_u[..., None], ids[safe], -1).astype(jnp.int32)
    w_slot = jnp.where(
        valid_u[..., None], weights[safe].astype(jnp.float32), 0.0
    )
    return tok_slot, ids_slot, w_slot, hit, u_counts


def _ep_moe_hier_device(x, logits, w_up, w_down, ctx: EPMoEContext):
    """Hierarchical EP with RAIL DEDUP: each token crosses DCN at most
    ONCE per target slice (not once per assignment), is expanded to its
    per-expert assignments INSIDE the slice, and its per-slice weighted
    partial crosses back as ONE row (≡ the reference's once-per-node
    put + intra-node scatter, ep_a2a.py:36-150; DCN is exactly the link
    where duplicate bytes hurt most)."""
    m = x.shape[0]
    dcn, epl, epr = ctx.dcn, ctx.epl, ctx.experts_per_rank
    weights, ids = mu.select_experts(logits, ctx.topk)
    ids = ids.astype(jnp.int32)

    tok_slot, ids_slot, w_slot, hit, _ = _rail_stage(ctx, x, ids, weights)

    # DCN rail (same-local-rank by mesh construction): unique tokens out
    rtok = jax.lax.all_to_all(tok_slot, ctx.dcn_axis, 0, 0, tiled=False)
    rids = jax.lax.all_to_all(ids_slot, ctx.dcn_axis, 0, 0, tiled=False)
    rw = jax.lax.all_to_all(w_slot, ctx.dcn_axis, 0, 0, tiled=False)

    # intra-slice flat EP over the railed set: keep only assignments
    # whose expert lives in MY slice, sentinel the rest
    my_slice = jax.lax.axis_index(ctx.dcn_axis)
    slice_experts = epl * epr
    rows = rtok.reshape(dcn * m, ctx.hidden)
    aids = rids.reshape(dcn * m, ctx.topk)
    local_e = aids - my_slice * slice_experts
    amask = (aids >= 0) & (local_e >= 0) & (local_e < slice_experts)
    flat_e = jnp.where(amask, local_e, slice_experts).reshape(-1)
    w_flat = jnp.where(amask, rw.reshape(dcn * m, ctx.topk), 0.0).reshape(-1)

    sub = replace(
        ctx,
        num_experts=slice_experts,
        max_m=ctx.max_m * dcn,
        dcn_axis=None,
        # honor the caller's transport on the intra-slice leg: "pallas"
        # keeps the padded-slot semantics (per-peer capacity with
        # overflow clamping); "fused"/"xla" pass through
        transport=ctx.transport,
    )
    part = _ep_assignments_device(
        sub, rows, flat_e, w_flat, dcn * m, w_up, w_down
    )                                                    # (dcn·m, H) f32

    # rail back: ONE weighted partial row per unique (token, slice) pair
    # — in ctx.dtype, not the f32 accumulator (DCN is exactly the link
    # where bytes hurt; the cross-slice sum still runs in f32 below)
    back = jax.lax.all_to_all(
        part.astype(ctx.dtype).reshape(dcn, m, ctx.hidden),
        ctx.dcn_axis, 0, 0, tiled=False,
    )
    # source side: sum each token's per-slice partials
    pos = jnp.cumsum(hit, axis=0) - 1                    # (m, dcn)
    safe_pos = jnp.clip(pos, 0, m - 1)
    d_idx = jnp.arange(dcn)
    gathered = back[d_idx[None, :], safe_pos]            # (m, dcn, H)
    out = jnp.sum(
        jnp.where(hit[..., None], gathered.astype(jnp.float32), 0.0),
        axis=1,
    )
    return out.astype(x.dtype)


def ep_moe_device(x, logits, w_up, w_down, ctx: EPMoEContext, state=None,
                  instance=0):
    """Per-device EP MoE body — callable inside any shard_map.

    x: (M, H) this rank's tokens; logits: (M, E); w_up: (epr, H, F),
    w_down: (epr, F, H) — this rank's experts. Returns (M, H), plus the
    updated LL workspace dict when ``state`` is given.
    """
    assert ctx.transport in ("fused", "pallas", "xla"), (
        f"unresolved transport {ctx.transport!r} — build contexts via "
        "create_ep_moe_context"
    )
    if state is not None and (ctx.transport != "fused" or ctx.dcn_axis):
        # reject here (not just in the ep_moe host entry): a state
        # silently dropped on a downgraded transport would surface as
        # None['parity'] a step later, far from the cause
        raise ValueError(
            "ep_moe_device state= rides the flat fused transport only "
            f"(got transport={ctx.transport!r}, dcn_axis={ctx.dcn_axis!r})"
        )
    if ctx.dcn_axis is not None:
        return _ep_moe_hier_device(x, logits, w_up, w_down, ctx)
    weights, ids = mu.select_experts(logits, ctx.topk)
    res = _ep_assignments_device(
        ctx, x, ids.reshape(-1).astype(jnp.int32),
        weights.reshape(-1).astype(jnp.float32), x.shape[0], w_up, w_down,
        state=state, instance=instance,
    )
    if state is not None:
        out, new_state = res
        return out.astype(x.dtype), new_state
    return res.astype(x.dtype)


@functools.lru_cache(maxsize=64)
def _build_ep_moe(ctx: EPMoEContext, ikey: tuple = (), instance=None):
    # ikey: config.interp_key() — chaos/race knobs are baked in at trace
    # time, so they must participate in the cache identity (like every
    # other kernel builder; del keeps the signature honest about usage).
    # instance: the EPMoEState identity (None → stateless barrier mode).
    del ikey
    rows = P(tuple(ctx.batch_axes) + ctx.ep_axes)
    experts = P(ctx.ep_axes)
    if instance is None:
        fn = jax.shard_map(
            functools.partial(ep_moe_device, ctx=ctx),
            mesh=ctx.mesh,
            in_specs=(rows, rows, experts, experts),
            out_specs=rows,
            check_vma=False,
        )
        return jax.jit(fn)
    ws_specs = {
        "parity": P(),
        "disp_tok": rows, "disp_meta": rows,
        "comb_tok": rows, "comb_meta": rows,
    }
    def body(x, logits, w_up, w_down, ws):
        return ep_moe_device(
            x, logits, w_up, w_down, ctx, state=ws, instance=instance
        )

    fn = jax.shard_map(
        body,
        mesh=ctx.mesh,
        in_specs=(rows, rows, experts, experts, ws_specs),
        out_specs=(rows, ws_specs),
        check_vma=False,
    )
    # donate the workspaces: the LL protocol REQUIRES the same physical
    # buffers to carry every call (skewed peers' in-flight DMAs target
    # the persistent addresses)
    return jax.jit(fn, donate_argnums=(4,))


def ep_moe(x, logits, w_up, w_down, ctx: EPMoEContext, state=None):
    """Host entry: EP MoE MLP on ``ctx.mesh``.

    Global shapes: x (M, H) and logits (M, E) token-sharded over
    ``ctx.axis``; w_up (E, H, F) / w_down (E, F, H) expert-sharded over
    ``ctx.axis``. Returns (M, H) token-sharded.

    With ``state`` (an :class:`EPMoEState` from
    :func:`create_ep_moe_state`): the fused transport runs BARRIER-FREE
    over the state's persistent double-buffered workspaces and the call
    returns ``(out, state')`` — thread ``state'`` into the next call
    (the reference's call_count protocol, low_latency_all_to_all.py:
    97-118, as a functional carry usable inside jitted decode loops).
    """
    from triton_distributed_tpu.config import interp_key

    reason = _transport_degrade_reason(ctx)
    if reason is not None:
        from triton_distributed_tpu.ops.overlap import _log_demotion_once

        _log_demotion_once("ep_moe", reason)
        demoted = replace(ctx, transport="xla")
        out = _build_ep_moe(demoted, interp_key())(x, logits, w_up, w_down)
        if state is not None:
            # the LL workspaces carry no obligations while the fused
            # transport is demoted — return them untouched so the caller's
            # state threading survives the degradation window
            return out, state
        return out
    if state is None:
        return _build_ep_moe(ctx, interp_key())(x, logits, w_up, w_down)
    if ctx.transport != "fused":
        raise ValueError("ep_moe state= requires transport='fused'")
    fn = _build_ep_moe(ctx, interp_key(), state.instance)
    out, ws = fn(x, logits, w_up, w_down, state.as_dict())
    return out, EPMoEState(instance=state.instance, **ws)


def _transport_degrade_reason(ctx: EPMoEContext) -> str | None:
    """Should the Pallas/fused MoE transport demote to the XLA a2a for
    this call? Same probe family as ``ops.overlap.preflight``: an
    unhealthy peer in the active fault plan or a prior watchdog trip.
    Quantized wire payloads cannot demote (the XLA transport is
    full-precision only) — those keep the fused path and surface
    whatever the fault is."""
    if ctx.transport not in ("fused", "pallas") or ctx.quant is not None:
        return None
    from triton_distributed_tpu.runtime import faults, watchdog

    plan = faults.active_plan()
    if plan is not None and plan.unhealthy_peers:
        return (
            f"fault plan marks peer(s) {plan.unhealthy_peers} unhealthy "
            f"(plan seed={plan.seed})"
        )
    if watchdog.last_trip() is not None:
        return "collective watchdog tripped on a prior step"
    from triton_distributed_tpu.runtime import health

    for ledger in health.live_ledgers():
        bad = ledger.unhealthy_peers()
        if bad:
            return f"health ledger marks peer(s) {bad} unhealthy"
    return None


_EP_MOE_TUNERS: OrderedDict = OrderedDict()
_EP_MOE_TUNERS_MAX = 64          # bounded like the sibling _build caches


def ep_moe_tuned(x, logits, w_up, w_down, ctx: EPMoEContext,
                 candidates: tuple = (64, 128, 256)):
    """``ep_moe`` with ``block_m`` autotuned per input shape.

    The L6→L4 integration the reference gets from wrapping kernels in
    ``contextual_autotune`` (autotuner.py:97): the whole thunk is
    benchmarked per block size (alignment capacity changes with it, so
    the tuning unit must be the op, not the inner GEMM), the winner is
    cached per shape, and on multi-process meshes the MAX-consensus
    keeps every process on the same config.
    """
    from triton_distributed_tpu.tune import ContextualAutoTuner  # cycle: tune→ops is none, but keep ops importable without tune at module load

    key = (ctx, tuple(candidates))
    tuner = _EP_MOE_TUNERS.get(key)
    if tuner is None:
        def run(x, logits, up, down, *, block_m):
            return ep_moe(x, logits, up, down, replace(ctx, block_m=block_m))

        # ctx is part of the tuner identity: the persistent winner store
        # keys on (name, arg shapes), and two contexts with identical
        # token shapes but different transport/quant/geometry must not
        # share winners
        ctx_tag = (
            f"{dict(ctx.mesh.shape)}|{ctx.axis}|{ctx.dcn_axis}|"
            f"E{ctx.num_experts}k{ctx.topk}m{ctx.max_m}|{ctx.transport}|"
            f"{ctx.quant}|{jnp.dtype(ctx.dtype).name}"
        )
        tuner = ContextualAutoTuner(
            run, [{"block_m": b} for b in candidates],
            name=f"ep_moe[{ctx_tag}]",
        )
        _EP_MOE_TUNERS[key] = tuner
        while len(_EP_MOE_TUNERS) > _EP_MOE_TUNERS_MAX:
            _EP_MOE_TUNERS.popitem(last=False)
    else:
        _EP_MOE_TUNERS.move_to_end(key)
    return tuner(x, logits, w_up, w_down)
