"""Flagship transformer: TP+SP(+DP) decoder built on the overlap ops.

The reference is a kernel library, not a training framework — its model
surface is the TP shapes its tests use (Llama-7B/70B GEMMs,
test_ag_gemm.py; DeepSeek MoE shapes, test_ep_moe_inference.py) and the
SP decode layer. This module is the framework-level completion: a
decoder whose every projection runs through the fused overlap ops, so
the reference's flagship patterns (AG-GEMM up/qkv, GEMM-RS down/out —
tutorials 07/08; MoE TP — ag_group_gemm/moe_reduce_rs; SP flash-decode
— sp_flash_decode_layer.py) ARE the model's hot path, for training and
decode alike.

Layout (Megatron sequence-parallel):

* Between blocks, activations are (B·S, H) row-sharded over
  (*dp_axes, tp) — the SP layout.
* qkv/up projections: AG-GEMM (gather rows, col-shard heads/ffn).
* out/down projections: GEMM-RS (row-shard K, scatter rows back).
* Attention runs with heads sharded over tp (plain jnp between the
  overlap ops — XLA keeps the head dim local, no resharding).
* MoE blocks: MoETPMLP (TP over experts' F dim) or EPMoEMLP (EP over
  the same axis) — selectable per config.
* LM head: weights replicated, rows stay sharded, loss is computed on
  the row shards (no logit gather).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_distributed_tpu import ops
from triton_distributed_tpu.kernels import moe_utils as mu
from triton_distributed_tpu.layers import (
    ColumnParallelLinear,
    ParallelMLP,
    RowParallelLinear,
    SpGQAFlashDecodeAttention,
)


@dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    n_layers: int = 2
    hidden: int = 512
    ffn: int = 1024
    n_heads: int = 8
    n_kv_heads: int = 4
    head_dim: int = 64
    # Attention parallelism: "tp" = heads sharded via AG-GEMM/GEMM-RS
    # projections; "ring" / "ulysses" = context parallelism over the tp
    # axis (sequence-sharded attention, replicated projection weights) —
    # the long-context training modes
    attn: str = "tp"
    # MoE: "none" = dense MLP everywhere; "tp" / "ep" put a MoE MLP in
    # every block whose index is in moe_layers
    moe: str = "none"
    moe_layers: tuple = ()
    num_experts: int = 8
    topk: int = 2
    norm_eps: float = 1e-5
    # Quantized wire for the fused EP-MoE DECODE transport ("fp8" |
    # "int8" | None): tokens cross the a2a at 1 byte/elem with
    # per-token scales in the metadata (≡ the reference's headline fp8
    # WITH_SCALE dispatch). Halves the decode wire bytes at n>1;
    # measured neutral at n=1 self-transport (docs/PERF.md). Training
    # and prefill paths are unaffected (they ride the differentiable
    # full-precision transport).
    moe_wire_quant: str | None = None
    # Weight-only quantization of the EP expert matrices ("int8" |
    # "fp8" | None): serving-decode grouped GEMMs are weight-HBM-bound
    # (B·topk rows vs MB-scale matrices), so 1-byte weights halve the
    # dominant read. Takes effect when the caller runs params through
    # :meth:`Transformer.quantize_moe_weights` (after init/load);
    # training/prefill paths widen transparently. TPU-first extension —
    # the reference quantizes only the moving tokens (WITH_SCALE fp8,
    # low_latency_all_to_all.py:82-90), not the stationary weights.
    moe_weight_quant: str | None = None
    # W8A8 expert GEMMs ("int8" | None): with int8 expert weights, also
    # quantize the decode activations per row and run the MXU's native
    # s8×s8 path at 2× the bf16 rate (ops/moe.EPMoEContext.act_quant).
    # Adds one more per-row quantization step on the hidden activation;
    # logits stay within ~1% of the W8A16 path (tests). Decode-only.
    moe_act_quant: str | None = None
    # Weight-only quantization of the DENSE projections ("int8" |
    # None): wqkv / wo / dense-MLP up/down / lm_head stored int8 with
    # per-out-channel f32 scales, consumed at DECODE time by the
    # grouped-GEMM epilogue-dequant kernel (E=1) — at decode the M dim
    # is B, so these matmuls are weight-HBM-bound exactly like the
    # expert GEMMs and 1-byte weights halve the dominant read. Takes
    # effect after :meth:`Transformer.quantize_dense_weights`;
    # prefill/training widen transparently. TPU-first extension.
    dense_weight_quant: str | None = None
    # W8A8 dense projections ("int8" | None): also quantize the B
    # activation rows per step so the dense decode matmuls ride the
    # s8×s8 MXU path. Requires dense_weight_quant="int8". The lm_head
    # stays W8A16 (logits want the f32 accumulator unperturbed by
    # input quantization); applies to wqkv/wo/up/down.
    dense_act_quant: str | None = None
    # INT8 KV cache ("int8" | None): decode caches store int8 values +
    # per-(b, head, position) f32 scales and the SP flash-decode kernel
    # folds the scales into the softmax — half the KV bytes at rest
    # (2× context per chip) and on the attention DMA stream (measured
    # 25–40% faster decode attention at serving shapes, docs/PERF.md).
    # TPU-first serving extension; prefill/training are unaffected.
    kv_quant: str | None = None
    # rematerialize each block in backward (jax.checkpoint): trades one
    # extra forward per block for O(n_layers) less activation memory —
    # the standard long-context / large-model training knob. Off-TPU the
    # INTERPRETED Pallas engines carry io_callback effects that
    # jax.checkpoint rejects — use the XLA engines there (e.g.
    # TDTPU_FUSED_VMEM_BUDGET=0); compiled Mosaic kernels compose fine.
    remat: bool = False
    dtype: object = jnp.bfloat16
    param_dtype: object = jnp.float32

    def __post_init__(self):
        if self.attn not in ("tp", "ring", "ulysses"):
            raise ValueError(
                f"attn must be 'tp', 'ring' or 'ulysses', got {self.attn!r}"
            )
        if self.moe not in ("none", "tp", "ep"):
            raise ValueError(
                f"moe must be 'none', 'tp' or 'ep', got {self.moe!r}"
            )
        if self.moe_wire_quant not in (None, "fp8", "int8"):
            raise ValueError(
                "moe_wire_quant must be None, 'fp8' or 'int8', got "
                f"{self.moe_wire_quant!r}"
            )
        if self.moe_weight_quant not in (None, "fp8", "int8"):
            raise ValueError(
                "moe_weight_quant must be None, 'fp8' or 'int8', got "
                f"{self.moe_weight_quant!r}"
            )
        if self.kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {self.kv_quant!r}"
            )
        if self.dense_weight_quant not in (None, "int8"):
            raise ValueError(
                "dense_weight_quant must be None or 'int8', got "
                f"{self.dense_weight_quant!r}"
            )
        if self.moe_act_quant not in (None, "int8"):
            raise ValueError(
                "moe_act_quant must be None or 'int8', got "
                f"{self.moe_act_quant!r}"
            )
        if self.moe_act_quant is not None and self.moe_weight_quant != "int8":
            raise ValueError(
                "moe_act_quant (W8A8) needs moe_weight_quant='int8' — the "
                "s8×s8 MXU path consumes int8 weight dicts"
            )
        if self.dense_act_quant not in (None, "int8"):
            raise ValueError(
                "dense_act_quant must be None or 'int8', got "
                f"{self.dense_act_quant!r}"
            )
        if (self.dense_act_quant is not None
                and self.dense_weight_quant != "int8"):
            raise ValueError(
                "dense_act_quant (W8A8) needs dense_weight_quant='int8'"
            )
        if self.moe_weight_quant is not None and self.moe != "ep":
            raise ValueError(
                "moe_weight_quant targets the EP expert matrices — set "
                f"moe='ep' (got moe={self.moe!r})"
            )

    @property
    def q_dim(self) -> int:
        return self.n_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    @property
    def qkv_dim(self) -> int:
        return self.q_dim + 2 * self.kv_dim


def _cache_capacity(caches):
    """Sequence capacity S of a per-layer cache list (plain bhsd arrays
    or int8 {"q", "scale"} dicts)."""
    ck = caches[0][0]
    return (ck["q"] if isinstance(ck, dict) else ck).shape[2]


def _serving_capacity(caches, block_table=None):
    """Capacity in sequence positions: the bhsd S dim for contiguous
    caches, R·pps·page for page pools."""
    if block_table is None:
        return _cache_capacity(caches)
    page = _cache_capacity(caches)      # dim 2 of a pool IS the page
    r, _, pps = block_table.shape
    return r * pps * page


def _update_q8(cache, q_new, s_new):
    """Write a quantized (B, Hkv, S', …) prefix into an int8 cache dict."""
    return {
        "q": jax.lax.dynamic_update_slice(cache["q"], q_new, (0, 0, 0, 0)),
        "scale": jax.lax.dynamic_update_slice(
            cache["scale"], s_new.astype(cache["scale"].dtype), (0, 0, 0)
        ),
    }


@dataclass(frozen=True)
class Transformer:
    """The model object: config + mesh/axes + derived contexts."""

    config: TransformerConfig
    mesh: Mesh
    tp_axis: str = "tp"
    dp_axes: tuple = ()
    # context-parallel axis for LONG-CONTEXT SERVING (None = no cp):
    # the serving page pool becomes cp stacked per-shard pools and each
    # shard's paged-attention partial merges through the cross-rank
    # LSE-combine (kernels/flash_decode.combine_gqa_partials; wire twin
    # cp_decode.lse_combine). Orthogonal to tp (head sharding) — a
    # tp×cp mesh shards heads within each cp group.
    cp_axis: str | None = None

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.tp_axis]

    @property
    def cp(self) -> int:
        """Context-parallel degree of the serving pool (1 = no cp)."""
        if not self.cp_axis:
            return 1
        return self.mesh.shape[self.cp_axis]

    @property
    def row_spec(self):
        """Sequence-parallel activation sharding: rows over (dp..., tp)."""
        return P(tuple(self.dp_axes) + (self.tp_axis,))

    @property
    def token_shards(self) -> int:
        """Number of row shards of the SP activation layout (tp × dp) —
        the single definition of the padding/shard-count arithmetic used
        by both prefill (EPMoEMLP) and decode (_decode_moe_ep)."""
        return self.tp * int(
            np.prod([self.mesh.shape[a] for a in self.dp_axes]) or 1
        )

    @functools.cached_property
    def _ag_ctx(self):
        return ops.create_ag_gemm_context(
            self.mesh, self.tp_axis, batch_axes=tuple(self.dp_axes)
        )

    @functools.cached_property
    def _rs_ctx(self):
        return ops.create_gemm_rs_context(
            self.mesh, self.tp_axis, batch_axes=tuple(self.dp_axes)
        )

    @functools.cached_property
    def _mlp(self):
        return ParallelMLP(
            ColumnParallelLinear(self._ag_ctx),
            RowParallelLinear(self._rs_ctx),
            activation="silu",
        )

    @functools.cached_property
    def _moe_tp_ctx(self):
        c = self.config
        return ops.create_ag_group_gemm_context(
            self.mesh, self.tp_axis, num_experts=c.num_experts, topk=c.topk,
            dtype=c.dtype, use_pallas_gemm=False,
            batch_axes=tuple(self.dp_axes),
        )

    def _moe_ep_ctx(self, m_local: int, inference: bool = False,
                    weights_quantized: bool | None = None):
        """``weights_quantized``: whether the expert-weight leaves this
        context will consume are ACTUALLY quantized dicts — the
        residency gate must size VMEM from the real storage, not from
        the config's intent (a preset may default moe_weight_quant
        while the caller never ran quantize_moe_weights; sizing bf16
        tiles at 1 B/elem would blow scoped VMEM at compile). None →
        trust the config (callers without params in hand, e.g.
        init_decode_state — residency affects only GEMM tiling, not
        state geometry)."""
        c = self.config
        # training must stay on the differentiable XLA transport;
        # inference (decode) rides the fused window-DMA dispatch — the
        # low-latency path the reference's EP-MoE serving scenario is
        # built around (test_ep_moe_inference.py). Two fallbacks to the
        # XLA transport: a tp axis that crosses DCN (no Pallas remote
        # DMA there — fall back like every other op entry, don't raise),
        # and off-TPU runs (per-step interpreted dispatch kernels are
        # 100× slower and can wedge the interpreter's worker pool — the
        # fused decode path's compile/correctness coverage lives in
        # tests/test_ep_moe.py, test_races.py and test_aot_topology.py).
        from triton_distributed_tpu.config import (
            compiling_for_tpu,
            config as _cfg,
        )
        from triton_distributed_tpu.runtime import is_dcn_axis

        # force_fused_transport: bounded off-TPU execution of the fused
        # transport on the interpreter (the multi-device execution
        # evidence for the composed fused-LL step) — transport only;
        # the Mosaic-only grouped-GEMM/W8A8 paths still need real
        # lowering (pallas_ok below)
        fused_ok = (
            inference
            and (compiling_for_tpu() or _cfg.force_fused_transport)
            and not is_dcn_axis(self.mesh, self.tp_axis)
        )
        pallas_ok = fused_ok and compiling_for_tpu()
        # the scalar-prefetch grouped-GEMM kernel in WEIGHT-RESIDENT
        # mode (whole-N/K tiles, block_m 64) wins the decode-size expert
        # MLP on hardware: less alignment padding without per-block
        # weight re-streaming (measured 2.60 → 1.83 ms/block at the
        # serving headline vs ragged_dot — see group_gemm.grouped_matmul
        # and docs/PERF.md's serving section); off-TPU / training keep
        # the differentiable ragged_dot path
        # weight residency needs one expert's FULL (hidden, ffn) matrix
        # double-buffered in VMEM — gate on the budget (e.g. Mixtral's
        # 117 MB expert exceeds a v5e's VMEM; fall back to the tiled
        # schedule at block_m 256, the tiled-sweep optimum)
        from triton_distributed_tpu.config import fused_vmem_budget
        from triton_distributed_tpu.kernels.group_gemm import (
            resident_weight_itemsize,
        )

        wq_mode = c.moe_weight_quant
        if weights_quantized is False:
            wq_mode = None               # raw bf16 leaves despite the config
        elif weights_quantized and wq_mode is None:
            # quantized dicts despite a None config (the explicit
            # mode= override of quantize_moe_weights): size the
            # residency gate from the 1-byte storage actually in hand
            wq_mode = "int8"
        w_itemsize = resident_weight_itemsize(wq_mode, c.dtype)
        wr_ok = pallas_ok and (
            2 * c.hidden * c.ffn * w_itemsize
            <= int(0.7 * fused_vmem_budget())
        )
        # W8A8 engages only where its int8 weight dicts will exist
        a8 = c.moe_act_quant if (pallas_ok and wq_mode == "int8") else None
        # block_m: W8A8's s8×s8 MXU rate needs ≥128-row blocks, while
        # W8A16 prefers 64 (less alignment padding; weight residency
        # removes the re-streaming penalty) — both measured, docs/PERF.md
        if wr_ok:
            bm = 128 if a8 else 64
        else:
            bm = 256 if pallas_ok else 128
        return ops.create_ep_moe_context(
            self.mesh, self.tp_axis, num_experts=c.num_experts, topk=c.topk,
            max_m=m_local * c.topk, hidden=c.hidden, dtype=c.dtype,
            transport="fused" if fused_ok else "xla",
            use_pallas_gemm=pallas_ok,
            block_m=bm,
            gg_block_n=1 << 30 if wr_ok else None,
            gg_block_k=1 << 30 if wr_ok else None,
            quant=c.moe_wire_quant if fused_ok else None,
            act_quant=a8,
            batch_axes=tuple(self.dp_axes),
        )

    # ---------------------------------------------------------------- params

    def init(self, key):
        c = self.config
        keys = iter(jax.random.split(key, 4 + 8 * c.n_layers))
        pd = c.param_dtype
        s = 1.0 / (c.hidden ** 0.5)

        def dense(k, shape, scale=None):
            return jax.random.normal(k, shape, pd) * (scale or s)

        params = {
            "embed": dense(next(keys), (c.vocab, c.hidden), 0.02),
            "norm_f": jnp.ones((c.hidden,), pd),
            "lm_head": dense(next(keys), (c.hidden, c.vocab)),
            "blocks": [],
        }
        for i in range(c.n_layers):
            blk = {
                "norm_attn": jnp.ones((c.hidden,), pd),
                "norm_mlp": jnp.ones((c.hidden,), pd),
                "wqkv": dense(next(keys), (c.hidden, c.qkv_dim)),
                "wo": dense(next(keys), (c.q_dim, c.hidden)),
            }
            if c.moe != "none" and i in c.moe_layers:
                blk["router"] = dense(next(keys), (c.hidden, c.num_experts))
                blk["moe_up"] = dense(next(keys), (c.num_experts, c.hidden, c.ffn))
                blk["moe_down"] = dense(
                    next(keys), (c.num_experts, c.ffn, c.hidden),
                    1.0 / (c.ffn ** 0.5),
                )
            else:
                blk["up"] = dense(next(keys), (c.hidden, c.ffn))
                blk["down"] = dense(
                    next(keys), (c.ffn, c.hidden), 1.0 / (c.ffn ** 0.5)
                )
            params["blocks"].append(blk)
        return params

    def quantize_moe_weights(self, params, mode: str | None = None):
        """Replace every EP block's expert matrices with weight-only-
        quantized ``{"q": 1-byte, "scale": (E, N) f32}`` dicts (see
        group_gemm.quantize_grouped_weights). Run AFTER init/load and
        device placement — the quantized leaves inherit the expert
        sharding from the source arrays. ``mode`` defaults to
        ``config.moe_weight_quant``; returns ``params`` unchanged when
        both are None. Decode consumes the dicts in the grouped-GEMM
        epilogue; prefill/training widen transparently."""
        mode = mode or self.config.moe_weight_quant
        if mode is None:
            return params
        if self.config.moe != "ep":
            raise ValueError("quantize_moe_weights targets EP expert weights")
        from triton_distributed_tpu.kernels.group_gemm import (
            quantize_grouped_weights,
        )

        out = dict(params)
        out["blocks"] = []
        for blk in params["blocks"]:
            blk = dict(blk)
            for name in ("moe_up", "moe_down"):
                if name in blk and not isinstance(blk[name], dict):
                    q, scale = quantize_grouped_weights(blk[name], mode)
                    blk[name] = {"q": q, "scale": scale}
            out["blocks"].append(blk)
        return out

    _DENSE_QUANT_KEYS = ("wqkv", "wo", "up", "down")

    def quantize_dense_weights(self, params, mode: str | None = None):
        """Replace the dense projection matrices (wqkv / wo / dense-MLP
        up/down per block, plus lm_head) with ``{"q": int8 (K, N),
        "scale": (N,) f32}`` dicts (per-out-channel, the same
        convention as the expert weights). Decode consumes them through
        the grouped-GEMM epilogue-dequant kernel; prefill/training
        widen transparently. Run AFTER init/load + device placement;
        ``mode`` defaults to ``config.dense_weight_quant``."""
        mode = mode or self.config.dense_weight_quant
        if mode is None:
            return params
        from triton_distributed_tpu.kernels.group_gemm import (
            quantize_grouped_weights,
        )

        def q2d(w):
            if isinstance(w, dict):
                return w                       # already quantized
            q, scale = quantize_grouped_weights(w[None], mode)
            return {"q": q[0], "scale": scale[0]}

        out = dict(params)
        out["lm_head"] = q2d(params["lm_head"])
        out["blocks"] = []
        for blk in params["blocks"]:
            blk = dict(blk)
            for name in self._DENSE_QUANT_KEYS:
                if name in blk:
                    blk[name] = q2d(blk[name])
            out["blocks"].append(blk)
        return out

    def _dense_w(self, w):
        """Dense weight for a widening consumer (prefill/training):
        dequantize a dict, cast a plain array to the compute dtype."""
        if isinstance(w, dict):
            from triton_distributed_tpu.kernels.group_gemm import (
                dequantize_grouped_weights,
            )

            return dequantize_grouped_weights(
                w["q"][None], w["scale"][None], self.config.dtype
            )[0]
        return w.astype(self.config.dtype)

    def _dmm(self, x, w, out_dtype=None, act_quant=True):
        """Decode-time dense matmul dispatching on the weight storage:
        quantized dicts ride the grouped-GEMM kernel (E=1, tiled weight
        streaming with epilogue dequant — the decode GEMMs are
        weight-HBM-bound, so 1-byte weights halve the dominant read);
        plain arrays take the ordinary XLA dot. With
        ``config.dense_act_quant`` (and ``act_quant=True``), the B
        activation rows quantize per row and the kernel runs the
        s8×s8 MXU path (W8A8)."""
        if not isinstance(w, dict):
            return x @ w.astype(out_dtype or self.config.dtype)
        from triton_distributed_tpu.config import fused_vmem_budget
        from triton_distributed_tpu.kernels.group_gemm import grouped_matmul

        b = x.shape[0]
        # ONE M-block (block_m = B): the grid iterates (m, n, k) with m
        # outermost, so a second M-block would re-stream every weight
        # tile — doubling the int8 reads back to bf16 volume (measured)
        if b > 1024:                             # huge M: decode never is
            y = x @ self._dense_w(w)
            return y.astype(out_dtype) if out_dtype is not None else y
        # sublane-odd B: pad rows up to the next multiple of 8 and slice
        # the result — the kernel path (f32 accumulator straight to the
        # store) then serves EVERY decode batch size; the old fallback
        # re-dequantized the full weight matrix in HBM per step and
        # rounded logits through bf16
        bp = -(-b // 8) * 8
        if bp != b:
            x = jnp.pad(x, ((0, bp - b), (0, 0)))
        kw = dict(
            w_scale=w["scale"][None], block_m=bp,
            vmem_limit_bytes=fused_vmem_budget(),
            out_dtype=out_dtype,
        )
        if (
            act_quant
            and self.config.dense_act_quant == "int8"
            and w["q"].dtype == jnp.int8
        ):
            from triton_distributed_tpu.kernels.group_gemm import (
                quantize_act_rows,
            )

            xq, xsc = quantize_act_rows(x)
            # pin the out dtype: W8A8 grouped_matmul would otherwise
            # default to bf16 (x is int8), silently downcasting an
            # f32 model's projection outputs
            kw["out_dtype"] = out_dtype or self.config.dtype
            y = grouped_matmul(
                xq, w["q"][None], jnp.zeros((1,), jnp.int32),
                x_scale=xsc, **kw,
            )
            return y[:b] if bp != b else y
        xp = x.astype(self.config.dtype)
        # out_dtype reaches the kernel store: the f32 accumulator casts
        # straight to it (an astype after a bf16 store would re-widen
        # already-rounded values — logits want full f32)
        y = grouped_matmul(
            xp, w["q"][None], jnp.zeros((1,), jnp.int32), **kw,
        )
        return y[:b] if bp != b else y

    def _expert_w(self, w):
        """Expert weights for a dense consumer: widen a quantized dict,
        cast a plain array."""
        if isinstance(w, dict):
            from triton_distributed_tpu.kernels.group_gemm import (
                dequantize_grouped_weights,
            )

            return dequantize_grouped_weights(
                w["q"], w["scale"], self.config.dtype
            )
        return w.astype(self.config.dtype)

    def shardings(self):
        """NamedSharding pytree matching :meth:`init` — TP dims sharded,
        the rest replicated (DP gradients reduce via batch_axes)."""
        c = self.config
        t = self.tp_axis

        def ns(*spec):
            return NamedSharding(self.mesh, P(*spec))

        rep = ns()
        out = {
            "embed": rep, "norm_f": rep, "lm_head": rep, "blocks": [],
        }
        for i in range(c.n_layers):
            if c.attn == "tp":
                attn_sh = {"wqkv": ns(None, t), "wo": ns(t, None)}
            else:
                # CP attention: projections replicated, sequence sharded
                attn_sh = {"wqkv": rep, "wo": rep}
            blk = {
                "norm_attn": rep, "norm_mlp": rep, **attn_sh,
            }
            if c.moe != "none" and i in c.moe_layers:
                if c.moe == "ep":
                    # experts sharded over tp (each rank owns E/tp experts)
                    blk.update(router=rep, moe_up=ns(t), moe_down=ns(t))
                else:
                    # TP flavour: the ffn dim sharded
                    blk.update(
                        router=rep,
                        moe_up=ns(None, None, t), moe_down=ns(None, t, None),
                    )
            else:
                blk.update(up=ns(None, t), down=ns(t, None))
            out["blocks"].append(blk)
        return out

    # --------------------------------------------------------------- forward

    def _rmsnorm(self, x, w):
        xf = x.astype(jnp.float32)
        r = jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.config.norm_eps
        )
        return (xf * r).astype(x.dtype) * w.astype(x.dtype)

    def _cp_attention(self, blk, x, b, s):
        """Context-parallel attention: sequence sharded over tp, heads
        whole, projection weights replicated (the long-context layout).
        x: (B·S, H) SP rows → ((B·S, H) SP rows, k, v)."""
        from triton_distributed_tpu.kernels.ring_attention import (
            ring_attention,
            ulysses_attention,
        )

        c = self.config
        ba = tuple(self.dp_axes)
        seq_sharding = NamedSharding(
            self.mesh, P(ba if ba else None, self.tp_axis)
        )
        xr = jax.lax.with_sharding_constraint(
            x.reshape(b, s, c.hidden), seq_sharding
        )
        qkv = xr @ self._dense_w(blk["wqkv"])                 # replicated W
        q, k, v = jnp.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=-1)
        q = q.reshape(b, s, c.n_heads, c.head_dim)
        k = k.reshape(b, s, c.n_kv_heads, c.head_dim)
        v = v.reshape(b, s, c.n_kv_heads, c.head_dim)
        attn = ring_attention if c.attn == "ring" else ulysses_attention
        o = attn(q, k, v, self.mesh, self.tp_axis, batch_axes=ba)
        o = o.reshape(b, s, c.q_dim) @ self._dense_w(blk["wo"])
        out = jax.lax.with_sharding_constraint(
            o.reshape(b * s, c.hidden),
            NamedSharding(self.mesh, self.row_spec),
        )
        return out, k, v

    def _attention_kv(self, blk, x, b, s):
        """Attention returning (out rows, k, v) — the K/V are what
        :meth:`prefill` writes into the decode caches. Dispatches to the
        context-parallel path for attn='ring'/'ulysses' (their K/V come
        back sequence-sharded, matching the seq-sharded caches)."""
        c = self.config
        if c.attn != "tp":
            return self._cp_attention(blk, x, b, s)
        qkv = ops.ag_gemm(x, self._dense_w(blk["wqkv"]), self._ag_ctx)
        q, k, v = jnp.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=-1)
        hq, hkv, d = c.n_heads, c.n_kv_heads, c.head_dim
        q = q.reshape(b, s, hq, d)
        k = k.reshape(b, s, hkv, d)
        v = v.reshape(b, s, hkv, d)
        g = hq // hkv
        qg = q.reshape(b, s, hkv, g, d)
        logits = jnp.einsum(
            "bshgd,bthd->bhgst", qg.astype(jnp.float32), k.astype(jnp.float32)
        ) / (d ** 0.5)
        mask = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(mask[None, None, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(c.dtype)
        o = jnp.einsum("bhgst,bthd->bshgd", probs, v)
        o = o.reshape(b * s, hq * d)
        out = ops.gemm_rs(o, self._dense_w(blk["wo"]), self._rs_ctx)
        return out, k, v

    def _attention(self, blk, x, b, s):
        """x: (B·S, H) SP rows → (B·S, H) SP rows. Heads sharded tp."""
        return self._attention_kv(blk, x, b, s)[0]

    def _mlp_block(self, blk, x, inference=False):
        c = self.config
        if "up" in blk:
            p = {
                "up": {"w": self._dense_w(blk["up"])},
                "down": {"w": self._dense_w(blk["down"])},
            }
            return self._mlp(p, x)
        moe_params = {
            "router": blk["router"],
            "up": self._expert_w(blk["moe_up"]),
            "down": self._expert_w(blk["moe_down"]),
        }
        if c.moe == "ep":
            # EP flavour: experts sharded over tp, tokens stay row-sharded;
            # fully differentiable (XLA transport) — the training MoE.
            from triton_distributed_tpu.layers import EPMoEMLP

            return EPMoEMLP(
                self._moe_ep_ctx(x.shape[0] // self.token_shards)
            )(moe_params, x)
        # TP flavour — one routing computation feeds either body
        logits = x.astype(jnp.float32) @ blk["router"]
        weights, ids = mu.select_experts(logits, c.topk)
        if inference and not self.dp_axes:
            # inference (no grads needed): the single-kernel overlapped
            # engines replace the composed differentiable pipeline
            from triton_distributed_tpu.ops import moe_tp_mlp_overlapped

            return moe_tp_mlp_overlapped(
                x, ids, weights, moe_params["up"], moe_params["down"],
                self._moe_tp_ctx,
            ).astype(c.dtype)
        from triton_distributed_tpu.layers import MoETPMLP

        return MoETPMLP(self._moe_tp_ctx)(moe_params, x, ids, weights)

    def _embed_rows(self, params, tokens):
        """(B, S) int32 → (B·S, H) SP-row-sharded activations."""
        x = params["embed"][tokens.reshape(-1)].astype(self.config.dtype)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, self.row_spec)
        )

    def _block(self, blk, x, b, s, inference=False):
        """One decoder block → (x, k, v). The SINGLE definition of the
        block math — forward and prefill both run exactly this (prefill
        keeps the k/v for cache filling; forward drops them);
        ``inference`` selects the non-differentiable overlapped engines
        where they exist (MoE-TP)."""
        xn = self._rmsnorm(x, blk["norm_attn"])
        # k/v are always produced; XLA dead-code-eliminates them when the
        # caller (forward) drops them
        h, k, v = self._attention_kv(blk, xn, b, s)
        x = x + h
        x = x + self._mlp_block(
            blk, self._rmsnorm(x, blk["norm_mlp"]), inference=inference
        )
        return x, k, v

    def _head(self, params, x):
        x = self._rmsnorm(x, params["norm_f"])
        w = params["lm_head"]
        if isinstance(w, dict):
            w = self._dense_w(w)
        return x.astype(jnp.float32) @ w

    def forward(self, params, tokens):
        """tokens: (B, S) int32 → logits (B·S, vocab) SP-row-sharded."""
        c = self.config
        b, s = tokens.shape
        x = self._embed_rows(params, tokens)

        def block(x, blk):
            return self._block(blk, x, b, s)[0]

        if c.remat:
            from triton_distributed_tpu.config import (
                _use_interpret,
                fused_vmem_budget,
            )

            if _use_interpret(None) and fused_vmem_budget() > 0:
                raise ValueError(
                    "remat=True off-TPU requires the XLA engines: the "
                    "interpreted Pallas engines carry io_callback effects "
                    "jax.checkpoint rejects. Set TDTPU_FUSED_VMEM_BUDGET=0 "
                    "(or config.config.fused_vmem_budget = 0) to pin them."
                )
            block = jax.checkpoint(block)
        for blk in params["blocks"]:
            x = block(x, blk)
        return self._head(params, x)

    def loss(self, params, tokens, targets):
        """Causal LM loss; logits stay row-sharded end to end."""
        logits = self.forward(params, tokens)
        tgt = targets.reshape(-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[:, None], axis=-1)[:, 0]
        return jnp.mean(nll)

    def train_step(self, params, tokens, targets, lr=1e-3):
        """One SGD step (the driver's dryrun entry; real training would
        wrap this in optax — the grads are ordinary pytrees)."""
        l, g = jax.value_and_grad(self.loss)(params, tokens, targets)
        new = jax.tree.map(lambda p, d: p - lr * d.astype(p.dtype), params, g)
        return l, new

    # ---------------------------------------------------------------- decode

    @functools.cached_property
    def _sp_attn(self):
        c = self.config
        return SpGQAFlashDecodeAttention(
            self.mesh, self.tp_axis, q_heads=c.n_heads,
            kv_heads=c.n_kv_heads, head_dim=c.head_dim,
            batch_axes=tuple(self.dp_axes),
        )

    @property
    def cache_sharding(self):
        """The ONE canonical KV-cache placement for the whole serving
        session: batch over dp, sequence over tp (dims 0 and 2 of both
        the (B, Hkv, S, D) planes and the (B, Hkv, S) int8 scales).
        init_cache places with it, prefill and decode_step pin their
        cache outputs to it, and the decode jits donate the caches —
        so the cache is SHARD-RESIDENT and updated in place for the
        life of the session (≡ sp_flash_decode_layer.py:45-184, whose
        per-rank KV shard never changes placement), with no
        involuntary remat/reshard across the prefill→decode boundary."""
        ba = tuple(self.dp_axes)
        return NamedSharding(
            self.mesh, P(ba if ba else None, None, self.tp_axis)
        )

    @property
    def batch_sharding(self):
        """(B,)-vector placement matching :attr:`cache_sharding`'s
        batch dim (kv_lens, last_tokens, per-row logits)."""
        ba = tuple(self.dp_axes)
        return NamedSharding(self.mesh, P(ba if ba else None))

    def _pin_caches(self, caches, paged=False):
        """with_sharding_constraint every cache leaf to the canonical
        :attr:`cache_sharding` (same spec covers the 4D planes and the
        3D scale leaves — batch dim 0, sequence dim 2); page pools pin
        their rank-major page dim over tp instead."""
        sh = self._paged_sharding if paged else self.cache_sharding
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, sh), caches
        )

    def init_cache(self, batch: int, max_len: int):
        """Per-layer (k, v) caches, (B, Hkv, S, D) ["bhsd", the fast
        decode layout — contiguous KV block DMAs] placed on
        :attr:`cache_sharding` — batch over dp, sequence over tp (≡ the
        KV sharding of sp_flash_decode_layer.py: each rank holds its
        slice of the sequence). With ``config.kv_quant``, each cache is
        a ``{"q": int8, "scale": (B, Hkv, S) f32}`` dict (the
        quantized-leaf convention shared with the expert weights)."""
        c = self.config
        spec = self.cache_sharding
        if c.kv_quant is not None:
            zq = jax.device_put(
                jnp.zeros(
                    (batch, c.n_kv_heads, max_len, c.head_dim), jnp.int8
                ),
                spec,
            )
            zs = jax.device_put(
                jnp.ones((batch, c.n_kv_heads, max_len), jnp.float32), spec
            )

            # EVERY leaf gets its own buffer (`+ 0` after placement):
            # the decode jits DONATE the caches, and donating one
            # physical buffer through two pytree leaves is a runtime
            # error ("attempt to donate the same buffer twice")
            def fresh():
                return {"q": zq + jnp.int8(0), "scale": zs + 0.0}

            return [(fresh(), fresh()) for _ in range(c.n_layers)]
        z = jnp.zeros((batch, c.n_kv_heads, max_len, c.head_dim), c.dtype)
        zz = jax.device_put(z, spec)
        return [
            (zz + jnp.zeros((), c.dtype), zz + jnp.zeros((), c.dtype))
            for _ in range(c.n_layers)
        ]

    @property
    def _paged_sharding(self):
        """Pool placement: pages (rank-major dim 0) over tp."""
        return NamedSharding(self.mesh, P(self.tp_axis))

    def init_paged_cache(self, batch: int, max_len: int, page: int = 1024):
        """PAGED twin of :meth:`init_cache` — the production serving
        mode (the reference's block-table path is its default decode
        entry, flash_decode.py:763-846). Returns ``(caches, table)``:
        per-layer (k_pool, v_pool) page pools of shape
        (R·B·pps, Hkv, page, D) sharded over tp on the page dim (rank
        r owns its sequence slice's pages), int8 ``{"q","scale"}``
        dicts under ``config.kv_quant``; and ONE (R, B, pps) block
        table of LOCAL page ids shared by every layer (dense identity
        allocation — a serving stack with its own allocator passes any
        table honoring the same contract). Paged mode is tp-only: the
        pool layout is rank-major, so dp composes by running one model
        per dp group."""
        c = self.config
        if self.dp_axes:
            raise ValueError("paged caches are tp-only (rank-major pools)")
        r = self.tp
        if max_len % (r * page):
            raise ValueError(
                f"capacity {max_len} must split into {r} rank slices of "
                f"whole {page}-row pages"
            )
        pps = max_len // r // page
        npages = r * batch * pps
        spec = self._paged_sharding
        table = jax.device_put(
            jnp.broadcast_to(
                jnp.arange(batch * pps, dtype=jnp.int32).reshape(
                    1, batch, pps
                ),
                (r, batch, pps),
            ),
            spec,
        )
        if c.kv_quant is not None:
            zq = jax.device_put(
                jnp.zeros((npages, c.n_kv_heads, page, c.head_dim),
                          jnp.int8),
                spec,
            )
            zs = jax.device_put(
                jnp.ones((npages, c.n_kv_heads, page), jnp.float32), spec
            )

            def fresh():
                # independent buffers per leaf — the decode jits donate
                return {"q": zq + jnp.int8(0), "scale": zs + 0.0}

            return [(fresh(), fresh()) for _ in range(c.n_layers)], table
        z = jax.device_put(
            jnp.zeros((npages, c.n_kv_heads, page, c.head_dim), c.dtype),
            spec,
        )
        zero = jnp.zeros((), c.dtype)
        return [(z + zero, z + zero) for _ in range(c.n_layers)], table

    def paginate_caches(self, caches, page: int = 1024):
        """Convert CONTIGUOUS (prefill-filled) caches into page pools +
        table — the prefill→paged-decode bridge: one reshape/transpose
        per plane, no gather (pages of the dense identity allocation
        are exactly the contiguous cache's page-aligned rows)."""
        r = self.tp

        def split(x):                       # (B, Hkv, S, D?) → pools
            b, hkv, s = x.shape[:3]
            tail = x.shape[3:]
            pps = s // r // page
            y = x.reshape((b, hkv, r, pps, page) + tail)
            # (R, B, pps, Hkv, page, tail) → rank-major page rows
            y = jnp.moveaxis(y, (2, 0, 3, 1), (0, 1, 2, 3))
            return jax.device_put(
                y.reshape((r * b * pps, hkv, page) + tail),
                self._paged_sharding,
            )

        out = []
        batch = None
        for ck, cv in caches:
            if isinstance(ck, dict):
                batch = ck["q"].shape[0]
                s = ck["q"].shape[2]
                ck = {"q": split(ck["q"]), "scale": split(ck["scale"])}
                cv = {"q": split(cv["q"]), "scale": split(cv["scale"])}
            else:
                batch, s = ck.shape[0], ck.shape[2]
                ck, cv = split(ck), split(cv)
            out.append((ck, cv))
        pps = s // r // page
        table = jax.device_put(
            jnp.broadcast_to(
                jnp.arange(batch * pps, dtype=jnp.int32).reshape(
                    1, batch, pps
                ),
                (r, batch, pps),
            ),
            self._paged_sharding,
        )
        return out, table

    def prefill(self, params, caches, tokens, lens=None):
        """Process a whole prompt batch in ONE forward pass and fill the
        decode caches: returns (per-row last-position logits (B, vocab),
        caches, kv_lens). The serving entry the reference leaves to the
        serving stack — :meth:`generate` continues from here instead of
        decoding the prompt token by token.

        ``lens`` (B,) enables RAGGED batches: rows are right-padded to S
        and ``lens[i]`` names row i's true prompt length. Causality makes
        the short rows' valid positions independent of their padding, the
        pad positions' K/V land beyond ``lens`` where decode never reads,
        and the returned logits are taken at each row's ``lens-1``.

        tokens: (B, S) int32, S ≤ cache capacity. Attention runs the
        forward path of the configured mode (TP: AG-GEMM qkv → dense
        causal softmax → GEMM-RS out; ring/ulysses: the CP kernels,
        whose K/V come back sequence-sharded like the caches) while the
        per-layer K/V are captured into the bhsd seq-sharded caches;
        MoE-TP blocks run the overlapped inference engines.
        """
        c = self.config
        b, s = tokens.shape
        cap = _cache_capacity(caches)
        assert s <= cap, f"prompt length {s} exceeds cache capacity {cap}"
        x = self._embed_rows(params, tokens)
        new_caches = []
        for blk, (ck, cv) in zip(params["blocks"], caches):
            x, k, v = self._block(blk, x, b, s, inference=True)
            kb = k.transpose(0, 2, 1, 3)              # (B, Hkv, S, D)
            vb = v.transpose(0, 2, 1, 3)
            if isinstance(ck, dict):                  # int8 cache
                from triton_distributed_tpu.kernels.flash_decode import (
                    quantize_kv,
                )

                ck = _update_q8(ck, *quantize_kv(kb))
                cv = _update_q8(cv, *quantize_kv(vb))
            else:
                ck = jax.lax.dynamic_update_slice(
                    ck, kb.astype(ck.dtype), (0, 0, 0, 0)
                )
                cv = jax.lax.dynamic_update_slice(
                    cv, vb.astype(cv.dtype), (0, 0, 0, 0)
                )
            new_caches.append((ck, cv))
        logits = self._head(params, x)
        if lens is None:
            lens = jnp.full((b,), s, jnp.int32)
        # clamp to the valid range: lens=0 would gather position -1 (the
        # last PAD) and lens>s would make decode attend over unwritten
        # cache rows — both silently wrong, neither assertable on traced
        # values
        lens = jnp.clip(lens.astype(jnp.int32), 1, s)
        last = logits.reshape(b, s, -1)[jnp.arange(b), lens - 1]
        # pin the serving state to the canonical placements so the
        # prefill outputs are bit-identical in placement to decode's
        # inputs — without this the dp×tp compile chooses freely and
        # XLA full-rematerializes the caches at the phase boundary
        # (last is pinned too: argmax over it produces the first decode
        # step's last_tokens already batch-over-dp)
        new_caches = self._pin_caches(new_caches)
        lens = jax.lax.with_sharding_constraint(lens, self.batch_sharding)
        last = jax.lax.with_sharding_constraint(last, self.batch_sharding)
        return last, new_caches, lens

    @functools.cached_property
    def _prefill_jit(self):
        # donate the (zero-filled) input caches: prefill writes into
        # them and the output placement equals the input placement
        # (cache_sharding), so XLA aliases instead of allocating a
        # second cache-sized buffer set
        return jax.jit(self.prefill, donate_argnums=(1,))
        # lens=None and lens=(B,) trace separately

    def init_decode_state(self, batch: int, abstract: bool = False):
        """Per-layer persistent workspaces for the BARRIER-FREE fused
        EP-MoE decode transport (ops.EPMoEState): one state per MoE
        layer, None elsewhere. Returns None when the model has no EP
        layers or decode would ride the XLA transport (off-TPU / DCN tp
        axis) — :meth:`decode_step` then needs no state at all.
        ``abstract=True`` yields ShapeDtypeStruct leaves (topology
        compiles)."""
        c = self.config
        if c.moe != "ep" or not c.moe_layers:
            return None
        m_local = -(-batch // self.token_shards)
        ctx = self._moe_ep_ctx(m_local, inference=True)
        if ctx.transport != "fused":
            return None
        from triton_distributed_tpu.ops import create_ep_moe_state

        return [
            create_ep_moe_state(ctx, abstract=abstract)
            if i in c.moe_layers else None
            for i in range(c.n_layers)
        ]

    def decode_step(self, params, caches, kv_lens, last_tokens,
                    moe_state=None, block_table=None):
        """One token of SP decode: replicated (B,) last tokens + seq-
        sharded caches → (B, vocab) logits, updated caches/lens.

        ``block_table`` switches to PAGED serving: ``caches`` are the
        page pools from :meth:`init_paged_cache` /
        :meth:`paginate_caches` and attention + append walk the table
        (≡ the reference's block-table decode default,
        flash_decode.py:763-846).

        Attention runs through the distributed flash-decode layer
        (local split-kv + AG(out,lse) + LSE combine); projections are
        plain matmuls — at decode the M dim is B, far too small for the
        overlap engines (matching the reference, whose decode path is
        the SP attention kernel, not AG-GEMM).

        ``moe_state`` (from :meth:`init_decode_state`): per-layer LL
        workspaces — EP-MoE blocks then run the fused transport
        BARRIER-FREE (≡ the reference's call_count protocol) and the
        step returns a 4th element, the updated state to thread into
        the next step.
        """
        c = self.config
        from triton_distributed_tpu.layers import append_kv

        x = params["embed"][last_tokens].astype(c.dtype)        # (B, H)
        # batch rows over dp end to end: the decode step is
        # data-parallel over dp (each dp group serves its rows against
        # its resident cache shards) — pinning x here keeps GSPMD from
        # electing a layout that replicates the caches
        x = jax.lax.with_sharding_constraint(x, self.batch_sharding)
        b = x.shape[0]
        new_caches = []
        new_states = None if moe_state is None else list(moe_state)
        from triton_distributed_tpu.kernels.flash_decode import (
            combine_partials,
        )

        for li, (blk, (ck, cv)) in enumerate(zip(params["blocks"], caches)):
            xn = self._rmsnorm(x, blk["norm_attn"])
            qkv = self._dmm(xn, blk["wqkv"])                    # (B, qkv)
            q, k, v = jnp.split(qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=-1)
            q = q.reshape(b, c.n_heads, c.head_dim)
            k = k.reshape(b, c.n_kv_heads, c.head_dim)
            v = v.reshape(b, c.n_kv_heads, c.head_dim)
            # attention over the OLD cache + the just-produced token as
            # an exact single-position softmax partial (its lse is the
            # raw score; weight-1 softmax over one position). The merge
            # is associative, so this equals attending over the
            # appended cache — WITHOUT the attention kernel reading the
            # append's scatter output (XLA serializes scatter→kernel
            # with a cache-sized copy pass; measured ~170 µs/step at
            # the serving shape). The append below only feeds the NEXT
            # step and schedules independently.
            kv_quant = None
            if isinstance(ck, dict):
                # int8 cache: every LATER step reads this token's
                # quantized form — attend it quantized NOW too, so the
                # step's logits are bit-consistent with re-running
                # attention over the appended quantized cache. The
                # append below receives the SAME (q, scale) pairs the
                # attention saw (re-quantizing the bf16 round-trip can
                # shift the recomputed ints by 1 LSB — ADVICE r5), so
                # the claimed bit-consistency is exact, not approximate.
                from triton_distributed_tpu.kernels.flash_decode import (
                    quantize_kv,
                )

                kq8, ks8 = quantize_kv(k)
                vq8, vs8 = quantize_kv(v)
                kv_quant = ((kq8, ks8), (vq8, vs8))
                k = (kq8.astype(jnp.float32) * ks8[..., None]).astype(k.dtype)
                v = (vq8.astype(jnp.float32) * vs8[..., None]).astype(v.dtype)
            o_c, lse_c = self._sp_attn.partials(
                q, ck, cv, kv_lens, block_table
            )
            # the token partial comes from the SAME layer so its score
            # convention (scale, soft_cap) cannot drift from the
            # kernel's lse domain
            o_new, lse_new = self._sp_attn.token_partial(q, k, v)
            o, _ = combine_partials(
                jnp.stack([o_c.astype(jnp.float32), o_new]),
                jnp.stack([lse_c, lse_new]),
                out_dtype=o_c.dtype,
            )
            kq_pair = kv_quant[0] if kv_quant is not None else None
            vq_pair = kv_quant[1] if kv_quant is not None else None
            if block_table is None:
                ck, cv, _ = append_kv(
                    ck, cv, kv_lens, k, v, kv_layout="bhsd",
                    k_quant=kq_pair, v_quant=vq_pair,
                )
            else:
                from triton_distributed_tpu.layers import paged_append_kv

                ck, cv, _ = paged_append_kv(
                    ck, cv, block_table, kv_lens, k, v,
                    k_quant=kq_pair, v_quant=vq_pair,
                )
            new_caches.append((ck, cv))
            o = self._dmm(o.reshape(b, c.q_dim), blk["wo"])
            x = x + o
            xn = self._rmsnorm(x, blk["norm_mlp"])
            if "up" in blk:
                h = jax.nn.silu(self._dmm(xn, blk["up"]))
                x = x + self._dmm(h, blk["down"])
            elif c.moe == "ep":
                st = None if moe_state is None else moe_state[li]
                y, st = self._decode_moe_ep(blk, xn, st)
                x = x + y.astype(x.dtype)
                if new_states is not None:
                    new_states[li] = st
            else:
                # TP flavour: experts replicated on the expert dim (only
                # F is sharded), so the per-topk gather stays shard-local
                # — (B, H, F/tp) per device, no cross-shard weight moves
                logits_r = xn.astype(jnp.float32) @ blk["router"]
                w, ids = mu.select_experts(logits_r, c.topk)
                y = jnp.zeros_like(xn, dtype=jnp.float32)
                for t in range(c.topk):
                    hh = jax.nn.silu(
                        jnp.einsum("bh,bhf->bf", xn, blk["moe_up"][ids[:, t]].astype(c.dtype))
                    )
                    y += w[:, t:t + 1] * jnp.einsum(
                        "bf,bfh->bh", hh, blk["moe_down"][ids[:, t]].astype(c.dtype)
                    ).astype(jnp.float32)
                x = x + y.astype(x.dtype)
        x = self._rmsnorm(x, params["norm_f"])
        if isinstance(params["lm_head"], dict):
            # W8A16 deliberately: logits take the f32 accumulator
            # without input-quantization noise
            logits = self._dmm(
                x, params["lm_head"], out_dtype=jnp.float32, act_quant=False
            )
        else:
            logits = x.astype(jnp.float32) @ params["lm_head"]
        # outputs pinned to the SAME placements as the inputs
        # (cache_sharding / batch over dp): with the decode jits'
        # donation this makes every step's cache update alias in place
        # — no cache-sized copy, no cross-step reshard
        new_caches = self._pin_caches(new_caches, paged=block_table is not None)
        new_lens = jax.lax.with_sharding_constraint(
            kv_lens + 1, self.batch_sharding
        )
        if moe_state is None:
            return logits, new_caches, new_lens
        return logits, new_caches, new_lens, new_states

    def _decode_moe_ep(self, blk, xn, state=None):
        """Decode-step EP MoE: the B last-token activations ride the EP
        dispatch → sharded grouped expert MLP → combine machinery, so
        expert weights STAY sharded — no gathered (B, H, F) weight
        tensor ever materializes (the reference's EP-MoE inference
        headline: test_ep_moe_inference.py, decode-sized batches through
        low_latency_all_to_all.py:36-118). B is padded up to the token
        -shard count; pad rows are discarded after the combine. With
        ``state``, the transport runs barrier-free over the persistent
        workspaces; returns (y, state')."""
        c = self.config
        b = xn.shape[0]
        shards = self.token_shards
        pad = (-b) % shards
        xp = jnp.pad(xn, ((0, pad), (0, 0)))
        logits = xp.astype(jnp.float32) @ blk["router"]
        wq = isinstance(blk["moe_up"], dict)
        ctx = self._moe_ep_ctx(
            (b + pad) // shards, inference=True, weights_quantized=wq
        )
        # quantized dicts pass straight through — the ops layer consumes
        # them on both the grouped-GEMM (epilogue dequant) and XLA
        # (widen) paths; only plain arrays need the compute-dtype cast
        w_up, w_down = (
            w if isinstance(w, dict) else w.astype(c.dtype)
            for w in (blk["moe_up"], blk["moe_down"])
        )
        if state is not None and ctx.transport == "fused":
            y, state = ops.ep_moe(xp, logits, w_up, w_down, ctx, state=state)
        else:
            y = ops.ep_moe(xp, logits, w_up, w_down, ctx)
        return y[:b], state

    def decode_abstract_args(self, params, caches, kv_lens, last_tokens):
        """``ShapeDtypeStruct`` twins of one decode step's arguments
        with the CANONICAL serving placements attached (caches on
        :attr:`cache_sharding`, lens/tokens on :attr:`batch_sharding`;
        params keep their live placements). Lower the decode jits from
        THESE when compile-checking the serving data flow (dryrun /
        shardguard tests): a program lowered from the live arrays
        reports those arrays' own shardings back, so a phase-boundary
        check against it could never fail."""

        def abs_(x, s):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

        return (
            jax.tree.map(lambda x: abs_(x, x.sharding), params),
            jax.tree.map(lambda x: abs_(x, self.cache_sharding), caches),
            abs_(kv_lens, self.batch_sharding),
            abs_(last_tokens, self.batch_sharding),
        )

    @functools.cached_property
    def _decode_jit(self):
        # donate caches + kv_lens: with the in/out placements pinned
        # (cache_sharding), XLA aliases the cache params to the cache
        # results and append_kv updates IN PLACE — the production entry
        # no longer pays a cache-sized copy per token (≡ the reference
        # kernels mutating the persistent cache tensors,
        # flash_decode.py:763-846)
        return jax.jit(self.decode_step, donate_argnums=(1, 2))

    @functools.cached_property
    def _decode_jit_state(self):
        def step(params, caches, kv_lens, last_tokens, moe_state,
                 block_table=None):
            return self.decode_step(params, caches, kv_lens, last_tokens,
                                    moe_state, block_table)

        # donate the caches/lens (in-place update, see _decode_jit) AND
        # the LL workspaces: the barrier-free protocol requires the
        # SAME physical buffers across steps (skewed peers' in-flight
        # DMAs target the persistent addresses)
        return jax.jit(step, donate_argnums=(1, 2, 4))

    def generate(self, params, caches, kv_lens, last_tokens, steps: int,
                 moe_state=None, block_table=None):
        """Greedy decode ``steps`` tokens. The whole decode step is one
        jitted program (cached across steps and calls by shape). With
        ``moe_state`` (init_decode_state), EP-MoE blocks run the
        barrier-free fused transport and the state comes back as a 4th
        result for continuation. With ``block_table``, caches are page
        pools (init_paged_cache / paginate_caches)."""
        cap = _serving_capacity(caches, block_table)
        try:
            max_len = int(np.asarray(kv_lens).max()) + steps
            assert max_len <= cap, (
                f"cache capacity {cap} < {max_len} needed — writes past "
                f"capacity are silently dropped (see layers.append_kv)"
            )
        except jax.errors.TracerArrayConversionError:
            pass  # traced lens: caller owns the capacity contract
        out = []
        for _ in range(steps):
            if moe_state is None:
                logits, caches, kv_lens = self._decode_jit(
                    params, caches, kv_lens, last_tokens,
                    block_table=block_table,
                )
            else:
                logits, caches, kv_lens, moe_state = self._decode_jit_state(
                    params, caches, kv_lens, last_tokens, moe_state,
                    block_table=block_table,
                )
            last_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out.append(last_tokens)
        toks = jnp.stack(out, axis=1)
        if moe_state is None:
            return toks, caches, kv_lens
        return toks, caches, kv_lens, moe_state

    @functools.cached_property
    def _generate_scan_jit(self):
        @functools.partial(
            jax.jit, static_argnums=(4,), donate_argnums=(1, 2, 5)
        )
        def run(params, caches, kv_lens, last_tokens, steps, moe_state,
                block_table=None):
            def body(carry, _):
                caches, lens, toks, state = carry
                if state is None:
                    logits, caches, lens = self.decode_step(
                        params, caches, lens, toks,
                        block_table=block_table,
                    )
                else:
                    logits, caches, lens, state = self.decode_step(
                        params, caches, lens, toks, state,
                        block_table=block_table,
                    )
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (caches, lens, toks, state), toks

            (caches, lens, toks, state), out = jax.lax.scan(
                body, (caches, kv_lens, last_tokens, moe_state),
                None, length=steps,
            )
            return out.swapaxes(0, 1), caches, lens, state

        return run

    def generate_scan(self, params, caches, kv_lens, last_tokens,
                      steps: int, moe_state=None, block_table=None):
        """Greedy-decode ``steps`` tokens ON DEVICE: one jitted program
        whose ``lax.scan`` carries the caches, lens, tokens and the LL
        MoE state across steps — no host round-trip per token. Same
        results as :meth:`generate` (the per-step twin kept for
        step-at-a-time callers and CI); behind a dispatch relay this is
        the serving entry (one dispatch per SEQUENCE instead of ~90 ms
        × steps). The functional ``EPMoEState`` carry exists precisely
        so the barrier-free fused transport can ride a scan; caches,
        lens and state are donated (in place across calls, like the
        per-step jits)."""
        cap = _serving_capacity(caches, block_table)
        try:
            max_len = int(np.asarray(kv_lens).max()) + steps
            assert max_len <= cap, (
                f"cache capacity {cap} < {max_len} needed — writes past "
                f"capacity are silently dropped (see layers.append_kv)"
            )
        except jax.errors.TracerArrayConversionError:
            pass  # traced lens: caller owns the capacity contract
        toks, caches, kv_lens, moe_state = self._generate_scan_jit(
            params, caches, kv_lens, last_tokens, steps, moe_state,
            block_table,
        )
        if moe_state is None:
            return toks, caches, kv_lens
        return toks, caches, kv_lens, moe_state

    # ------------------------------------------------------- ragged serving

    @property
    def _serving_pool_sharding(self):
        """Serving pool placement: KV HEADS (dim 1) over tp. Heads are
        independent in GQA attention, so the ragged serving step never
        exchanges LSE partials across ranks — and the whole page pool
        (dim 0) is one shared allocation any rank can serve any request
        from, which is what the engine's single free list requires.
        (The decode path's sequence sharding instead concentrates a
        short request's pages — and its attention work — on rank 0.)"""
        return NamedSharding(self.mesh, P(None, self.tp_axis))

    def init_serving_state(self, slots: int, npages: int, page: int):
        """Build a fresh :class:`~triton_distributed_tpu.serving.state.
        ServingState` — the explicit serving-state object replacing the
        ``init_paged_cache``/``paginate_caches`` tuple plumbing for the
        continuous-batching engine: per-layer head-sharded page pools,
        one shared (slots, pages_per_seq) block table (allocator-owned,
        -1 = unallocated), per-slot kv_lens and cursors. Every leaf
        gets its own buffer (the serving-step jit donates the state).
        ``pages_per_seq`` is ``npages`` capped at 1024 table columns —
        a slot may address the whole pool.

        Under ``cp > 1``, ``npages`` is the PER-SHARD pool size: the
        pool rows become one stacked allocation of ``cp·npages`` pages
        (shard r owns rows [r·npages, (r+1)·npages) — on a cp-sharded
        TPU mesh this dim would carry P(cp_axis); this reproduction
        keeps the stack replicated and shards the attention WALK), the
        table columns split the same way, and one slot's capacity
        grows to ``cp·pages_per_shard·page`` positions — the whole
        point of long-context serving."""
        from triton_distributed_tpu.serving.state import (
            ServingState,
            fresh_table,
        )

        c = self.config
        cp = self.cp
        if self.dp_axes:
            raise ValueError("ragged serving is tp-only (dp composes by "
                             "running one engine per dp group)")
        if c.n_kv_heads % self.tp:
            raise ValueError(
                f"serving pools shard the {c.n_kv_heads} KV heads over "
                f"tp={self.tp} — Hkv must divide"
            )
        pps = min(npages, max(1024 // cp, 1)) * cp
        npages = npages * cp
        spec = self._serving_pool_sharding
        if c.kv_quant is not None:
            zq = jax.device_put(
                jnp.zeros((npages, c.n_kv_heads, page, c.head_dim),
                          jnp.int8),
                spec,
            )
            zs = jax.device_put(
                jnp.ones((npages, c.n_kv_heads, page), jnp.float32), spec
            )

            def pool():
                # independent buffers per leaf — the step jit donates
                return {"q": zq + jnp.int8(0), "scale": zs + 0.0}

            layers = tuple(
                (pool(), pool()) for _ in range(c.n_layers)
            )
        else:
            z = jax.device_put(
                jnp.zeros((npages, c.n_kv_heads, page, c.head_dim),
                          c.dtype),
                spec,
            )
            zero = jnp.zeros((), c.dtype)
            layers = tuple((z + zero, z + zero) for _ in range(c.n_layers))
        return ServingState(
            layers=layers,
            block_table=jnp.asarray(fresh_table(slots, pps)),
            kv_lens=jnp.zeros((slots,), jnp.int32),
            cursors=jnp.zeros((slots,), jnp.int32),
            page=page,
            cp=cp,
        )

    def _ragged_attn(self, qp, k_pool, v_pool, state, q_lens, q_starts,
                     block_q, use_pallas, n_bufs=2, topologies=None,
                     with_lse=False):
        """One layer's ragged paged attention over the (updated) pools
        via the head-sharded serving layer. qp: (Hkv, T·G, D) packed
        GQA rows (already holding this step's tokens in the pools —
        append-then-attend). Returns (Hkv, T·G, D) — or the
        ``(out, lse)`` partial pair under ``with_lse`` (the cp shard
        loop merges those via ``combine_gqa_partials``)."""
        from triton_distributed_tpu.layers import RaggedPagedAttention

        c = self.config
        layer = RaggedPagedAttention(
            self.mesh, self.tp_axis, group=c.n_heads // c.n_kv_heads,
            use_pallas=use_pallas,
        )
        return layer(
            qp, k_pool, v_pool, state.kv_lens, q_lens, q_starts,
            state.block_table, topologies=topologies, block_q=block_q,
            n_bufs=n_bufs, with_lse=with_lse,
        )

    def _cp_ragged_attn(self, qp, kp, vp, state, q_lens, q_starts,
                        block_q, use_pallas, n_bufs, topologies):
        """Context-parallel attention: walk each cp shard's slice of
        the stacked pool with a TOPO_CP row descriptor (the frontier
        shift makes each shard's local causal mask exact against the
        GLOBAL positions it holds), then merge the per-shard (out, lse)
        partials with the cross-rank LSE-combine — the XLA body of the
        ``cp_decode.lse_combine`` wire contract. Shard r of the table
        columns/pool rows is sliced statically; its local kv length and
        shift derive from the traced global ``state.kv_lens``. A row
        fully resident on shard 0 merges bit-exactly to shard 0's out
        (every other shard's lse is NEG_INF), which keeps short-request
        streams byte-identical to a cp-free engine."""
        from triton_distributed_tpu.kernels.flash_decode import (
            combine_gqa_partials,
        )
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            TOPO_CP,
            topo_width,
        )

        cp = state.cp
        pps_loc = state.pages_per_seq // cp
        pool0 = kp["q"] if isinstance(kp, dict) else kp
        nps = pool0.shape[0] // cp
        s_loc = pps_loc * state.page
        slots = state.slots
        if topologies is None:
            w = topo_width(block_q)
            topologies = jnp.zeros((slots, 2 + 2 * w), jnp.int32)
        outs, lses = [], []
        for r in range(cp):
            kp_r = jax.tree.map(lambda a: a[r * nps:(r + 1) * nps], kp)
            vp_r = jax.tree.map(lambda a: a[r * nps:(r + 1) * nps], vp)
            cols = state.block_table[:, r * pps_loc:(r + 1) * pps_loc]
            table_r = jnp.where(cols >= 0, cols - r * nps, -1)
            lens_r = jnp.clip(state.kv_lens - r * s_loc, 0, s_loc)
            shift_r = jnp.maximum(state.kv_lens - r * s_loc, 0) - lens_r
            topo_r = (
                topologies.at[:, 0].set(TOPO_CP).at[:, 1].set(shift_r)
            )
            o_r, l_r = self._ragged_attn(
                qp, kp_r, vp_r,
                state.replace(
                    layers=(), block_table=table_r, kv_lens=lens_r
                ),
                q_lens, q_starts, block_q, use_pallas, n_bufs, topo_r,
                with_lse=True,
            )
            outs.append(o_r)
            lses.append(l_r)
        out, _ = combine_gqa_partials(
            jnp.stack(outs), jnp.stack(lses), out_dtype=qp.dtype
        )
        return out

    def serving_step(self, params, state, tokens, token_rows, token_pos,
                     q_starts, q_lens, topologies=None, moe_state=None, *,
                     block_q: int = 8, use_pallas: bool = True,
                     n_bufs: int = 2, all_logits: bool = False):
        """One CONTINUOUS-BATCHING step: a ragged mixed batch of prefill
        chunks and decode tokens through every layer in one program.

        ``state``: :class:`ServingState` whose ``kv_lens`` already
        INCLUDE this step's tokens (the engine advances lengths at
        batch-assembly time); ``tokens``: (T,) packed token ids;
        ``token_rows``/``token_pos``: (T,) per-token slot id and global
        sequence position (pos < 0 marks padding tokens — their K/V
        writes are dropped); ``q_starts``/``q_lens``: (slots,) per-slot
        spans into the packed array (8-aligned starts, ``q_lens == 0``
        for slots not in this batch). Returns ``(logits (slots, vocab),
        state')`` — logits at each slot's LAST packed token (the
        next-token distribution for rows that finished a chunk at their
        prompt end, garbage for q_lens == 0 slots), plus ``moe_state'``
        threaded as in :meth:`decode_step` when given.

        ``topologies``: optional (slots, 2+2W) int32 per-row attention-
        topology descriptors (kernels/ragged_paged_attention.py layout)
        shared by every layer's attention — TREE verify rows, shared-
        prefix aliasing, and the ``q_lens == 0`` kernel-side row skip
        all ride this operand; None keeps the pre-topology launch.

        Every new K/V token is scattered into the page pools FIRST and
        attention reads the updated pools (append-then-attend): a
        prefill chunk's tokens attend each other causally through the
        pool, and under ``kv_quant`` they are attended in their stored
        int8 form — bit-consistent with every later step by
        construction."""
        from triton_distributed_tpu.kernels.ragged_paged_attention import (
            pack_gqa_rows,
            unpack_gqa_rows,
        )

        c = self.config
        t = tokens.shape[0]
        page = state.page
        npages = state.npages
        x = params["embed"][tokens].astype(c.dtype)          # (T, H)
        valid = token_pos >= 0
        pos_c = jnp.maximum(token_pos, 0)
        local_page = state.block_table[
            jnp.clip(token_rows, 0, state.slots - 1),
            jnp.clip(pos_c // page, 0, state.pages_per_seq - 1),
        ]
        # padding tokens (and unallocated -1 table entries) scatter out
        # of pool — JAX OOB-scatter drops them
        pool_idx = jnp.where(
            valid & (local_page >= 0), local_page, npages
        )
        off = pos_c % page
        heads = jnp.arange(c.n_kv_heads)
        pi = pool_idx[:, None]
        hi = heads[None, :]
        oi = off[:, None]

        new_layers = []
        new_states = None if moe_state is None else list(moe_state)
        for li, (blk, (kp, vp)) in enumerate(
            zip(params["blocks"], state.layers)
        ):
            xn = self._rmsnorm(x, blk["norm_attn"])
            qkv = self._dmm(xn, blk["wqkv"])                 # (T, qkv)
            q, k, v = jnp.split(
                qkv, [c.q_dim, c.q_dim + c.kv_dim], axis=-1
            )
            k = k.reshape(t, c.n_kv_heads, c.head_dim)
            v = v.reshape(t, c.n_kv_heads, c.head_dim)
            if isinstance(kp, dict):
                from triton_distributed_tpu.kernels.flash_decode import (
                    quantize_kv,
                )

                kq8, ks8 = quantize_kv(k)
                vq8, vs8 = quantize_kv(v)
                kp = {
                    "q": kp["q"].at[pi, hi, oi].set(kq8),
                    "scale": kp["scale"].at[pi, hi, oi].set(ks8),
                }
                vp = {
                    "q": vp["q"].at[pi, hi, oi].set(vq8),
                    "scale": vp["scale"].at[pi, hi, oi].set(vs8),
                }
            else:
                kp = kp.at[pi, hi, oi].set(k.astype(kp.dtype))
                vp = vp.at[pi, hi, oi].set(v.astype(vp.dtype))
            kp = jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(
                    a, self._serving_pool_sharding
                ), kp,
            )
            vp = jax.tree.map(
                lambda a: jax.lax.with_sharding_constraint(
                    a, self._serving_pool_sharding
                ), vp,
            )
            new_layers.append((kp, vp))
            qp = pack_gqa_rows(
                q.reshape(t, c.n_heads, c.head_dim), c.n_kv_heads
            )
            if state.cp > 1:
                o = self._cp_ragged_attn(
                    qp, kp, vp, state.replace(layers=()), q_lens,
                    q_starts, block_q, use_pallas, n_bufs, topologies,
                )
            else:
                o = self._ragged_attn(
                    qp, kp, vp, state.replace(layers=()), q_lens,
                    q_starts, block_q, use_pallas, n_bufs, topologies,
                )
            o = unpack_gqa_rows(o, c.n_heads).reshape(t, c.q_dim)
            x = x + self._dmm(o.astype(c.dtype), blk["wo"])
            xn = self._rmsnorm(x, blk["norm_mlp"])
            if "up" in blk:
                h = jax.nn.silu(self._dmm(xn, blk["up"]))
                x = x + self._dmm(h, blk["down"])
            elif c.moe == "ep":
                st = None if moe_state is None else moe_state[li]
                y, st = self._decode_moe_ep(blk, xn, st)
                x = x + y.astype(x.dtype)
                if new_states is not None:
                    new_states[li] = st
            else:
                logits_r = xn.astype(jnp.float32) @ blk["router"]
                w, ids = mu.select_experts(logits_r, c.topk)
                y = jnp.zeros_like(xn, dtype=jnp.float32)
                for tt in range(c.topk):
                    hh = jax.nn.silu(jnp.einsum(
                        "bh,bhf->bf", xn,
                        blk["moe_up"][ids[:, tt]].astype(c.dtype),
                    ))
                    y += w[:, tt:tt + 1] * jnp.einsum(
                        "bf,bfh->bh", hh,
                        blk["moe_down"][ids[:, tt]].astype(c.dtype),
                    ).astype(jnp.float32)
                x = x + y.astype(x.dtype)
        x = self._rmsnorm(x, params["norm_f"])
        if all_logits:
            # logits at EVERY packed position — the speculative verify
            # pass needs the next-token distribution after each draft
            # token, not just each slot's frontier. Per-token matmul
            # rows are independent, so logits[q_starts[s]+j] is
            # bit-identical to what a non-speculative step would have
            # produced at that sequence position.
            x_last = x                                       # (T, H)
        else:
            last_idx = jnp.clip(q_starts + q_lens - 1, 0, t - 1)
            x_last = x[last_idx]                             # (slots, H)
        if isinstance(params["lm_head"], dict):
            logits = self._dmm(
                x_last, params["lm_head"], out_dtype=jnp.float32,
                act_quant=False,
            )
        else:
            logits = x_last.astype(jnp.float32) @ params["lm_head"]
        new_state = state.replace(layers=tuple(new_layers))
        if moe_state is None:
            return logits, new_state
        return logits, new_state, new_states

    @functools.cached_property
    def _serving_jit(self):
        # donate the ServingState (pool append aliases in place — the
        # same discipline as the decode jits) and the LL MoE workspaces
        @functools.partial(
            jax.jit, static_argnums=(9, 10, 11), donate_argnums=(1, 8)
        )
        def step(params, state, tokens, token_rows, token_pos, q_starts,
                 q_lens, topologies, moe_state, block_q, use_pallas,
                 n_bufs=2):
            return self.serving_step(
                params, state, tokens, token_rows, token_pos, q_starts,
                q_lens, topologies, moe_state, block_q=block_q,
                use_pallas=use_pallas, n_bufs=n_bufs,
            )

        return step

    @functools.cached_property
    def _serving_all_logits_jit(self):
        # the speculative engine's serving step: identical batch
        # contract, but logits come back for EVERY packed position
        # ((T, vocab), not (slots, vocab)) so the engine can read the
        # verify row's distribution after each draft token. Same
        # donation discipline as `_serving_jit`.
        @functools.partial(
            jax.jit, static_argnums=(9, 10, 11), donate_argnums=(1, 8)
        )
        def step(params, state, tokens, token_rows, token_pos, q_starts,
                 q_lens, topologies, moe_state, block_q, use_pallas,
                 n_bufs=2):
            return self.serving_step(
                params, state, tokens, token_rows, token_pos, q_starts,
                q_lens, topologies, moe_state, block_q=block_q,
                use_pallas=use_pallas, n_bufs=n_bufs, all_logits=True,
            )

        return step
