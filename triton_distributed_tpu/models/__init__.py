"""Model package: the flagship TP+SP(+DP) transformer (dense and MoE).

The reference ships kernels, not models (SURVEY.md §0); this package is
the framework-level completion — decoders whose projections run through
the fused overlap ops so the reference's flagship patterns are the hot
path of a real model, trainable and decodable.
"""

from triton_distributed_tpu.models import presets
from triton_distributed_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
)

__all__ = ["Transformer", "TransformerConfig", "presets"]
