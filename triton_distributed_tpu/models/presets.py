"""Model-family presets on the north-star shapes.

The reference benchmarks its kernels on Llama-7B/70B TP GEMMs
(test_ag_gemm.py defaults, BASELINE.json) and DeepSeek-style MoE
AllToAll shapes (README.md:87); these presets pin the same families as
runnable model configs — full-size for deployment, "tiny" twins with
identical topology for tests/CI.
"""

from __future__ import annotations

import jax.numpy as jnp

from triton_distributed_tpu.models.transformer import TransformerConfig


def llama_7b(**overrides) -> TransformerConfig:
    """Llama-2-7B geometry (the reference's intra-node AG-GEMM bench
    family: hidden 4096, ffn 11008)."""
    cfg = dict(
        vocab=32000, n_layers=32, hidden=4096, ffn=11008,
        n_heads=32, n_kv_heads=32, head_dim=128,
        dtype=jnp.bfloat16,
    )
    cfg.update(overrides)
    return TransformerConfig(**cfg)


def llama_70b(**overrides) -> TransformerConfig:
    """Llama-2-70B geometry (GQA 8 KV heads; the inter-node bench
    family: hidden 8192, ffn 28672)."""
    cfg = dict(
        vocab=32000, n_layers=80, hidden=8192, ffn=28672,
        n_heads=64, n_kv_heads=8, head_dim=128,
        dtype=jnp.bfloat16,
    )
    cfg.update(overrides)
    return TransformerConfig(**cfg)


def mixtral_8x7b(**overrides) -> TransformerConfig:
    """Mixtral-style MoE: 8 experts topk 2 in every block (the EP a2a
    + grouped-GEMM family)."""
    cfg = dict(
        vocab=32000, n_layers=32, hidden=4096, ffn=14336,
        n_heads=32, n_kv_heads=8, head_dim=128,
        moe="ep", moe_layers=tuple(range(32)), num_experts=8, topk=2,
        dtype=jnp.bfloat16,
    )
    cfg.update(overrides)
    return TransformerConfig(**cfg)


def deepseek_moe_16b(**overrides) -> TransformerConfig:
    """DeepSeek-MoE-16B-style geometry: many small experts, topk 6
    (the low-latency AllToAll headline family, README.md:87)."""
    cfg = dict(
        vocab=102400, n_layers=28, hidden=2048, ffn=1408,
        n_heads=16, n_kv_heads=16, head_dim=128,
        moe="ep", moe_layers=tuple(range(1, 28)), num_experts=64, topk=6,
        dtype=jnp.bfloat16,
        # the reference's headline dispatch for this family is fp8
        # WITH_SCALE (README.md:87) — decode tokens cross the EP a2a at
        # 1 byte/elem with per-token scales (models/transformer.py)
        moe_wire_quant="fp8",
        # decode grouped GEMMs are weight-HBM-bound — serve the expert
        # matrices int8 (per-out-channel scales, epilogue dequant;
        # run params through Transformer.quantize_moe_weights). int8,
        # not fp8: v5e has no native fp8 MXU path and the widening
        # lowers poorly (docs/PERF.md dead-end record)
        moe_weight_quant="int8",
        # W8A8 expert GEMMs at decode: the MXU's s8×s8 path runs 2× the
        # bf16 rate and the wire already quantized the tokens
        moe_act_quant="int8",
        # int8 KV cache: half the cache HBM (2× context per chip) and
        # 25–40% faster decode attention (docs/PERF.md)
        kv_quant="int8",
        # int8 dense projections (wqkv/wo/lm_head): decode-time dense
        # GEMMs are weight-HBM-bound like the expert GEMMs — run params
        # through Transformer.quantize_dense_weights
        dense_weight_quant="int8",
        # W8A8 dense projections (lm_head stays W8A16 for the logits)
        dense_act_quant="int8",
    )
    cfg.update(overrides)
    return TransformerConfig(**cfg)


def tiny(preset=None, **overrides) -> TransformerConfig:
    """CI-sized twin: same topology knobs as ``preset`` (or dense
    defaults), tiny dims — what the tests and the driver dryrun use."""
    cfg = dict(
        vocab=128, n_layers=2, hidden=128, ffn=256,
        n_heads=8, n_kv_heads=4, head_dim=16,
        dtype=jnp.float32, param_dtype=jnp.float32,
    )
    if preset is not None:
        cfg.update(
            moe=preset.moe,
            moe_layers=tuple(i for i in preset.moe_layers if i < 2),
            num_experts=min(preset.num_experts, 8),
            topk=min(preset.topk, 2),
            attn=preset.attn,
            moe_wire_quant=preset.moe_wire_quant,
            moe_weight_quant=preset.moe_weight_quant,
            moe_act_quant=preset.moe_act_quant,
            kv_quant=preset.kv_quant,
            dense_weight_quant=preset.dense_weight_quant,
            dense_act_quant=preset.dense_act_quant,
        )
    cfg.update(overrides)
    return TransformerConfig(**cfg)
