"""Global configuration and platform detection.

The reference framework configures itself through env vars
(``NVSHMEM_*``, ``USE_TRITON_DISTRIBUTED_AOT``; reference:
python/triton_dist/layers/nvidia/sp_flash_decode_layer.py:32-39). Here the
switches that matter are: which backend are we on (TPU vs CPU-simulated
mesh), whether Pallas kernels should run under the TPU interpreter (the
CPU path used by the test-suite), and test-only chaos/race knobs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def backend() -> str:
    import jax

    return jax.default_backend()


def on_tpu() -> bool:
    return backend() == "tpu"


def compiling_for_tpu() -> bool:
    """Will Pallas kernels built now lower through Mosaic? True on real
    TPU and under ``force_compile`` (AOT lowering for an unattached TPU
    topology from a CPU-backed process). Strict Mosaic constraints
    (block alignment) key on this, not on :func:`on_tpu`."""
    return config.force_compile or on_tpu()


@dataclass
class Config:
    # Force Pallas interpreter mode even on TPU (debugging).
    force_interpret: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_FORCE_INTERPRET", "0") == "1"
    )
    # Force real Mosaic compilation even off-TPU — the AOT-lowering path:
    # building kernels against an unattached multi-chip TPU *topology*
    # (jax.experimental.topologies) from a CPU-backed process must lower
    # through Mosaic, not the interpreter (tests/test_aot_topology.py).
    force_compile: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_FORCE_COMPILE", "0") == "1"
    )
    # Enable the interpreter's DMA race detector (CPU test runs only).
    # TPU-native answer to the reference's chaos-delay substitute for a race
    # detector (reference: python/triton_dist/kernels/nvidia/allgather.py:72-77).
    detect_races: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_DETECT_RACES", "0") == "1"
    )
    # Inject randomized delays into comm paths to widen race windows
    # ("for_correctness" testing in the reference).
    chaos_delay: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_CHAOS_DELAY", "0") == "1"
    )
    # Debug-mode integrity verification of the fused MoE transport's
    # wire metadata (kernels/moe_dispatch): senders always stamp a
    # checksum word into the meta head; with this flag on, receivers
    # re-verify it and POISON failing slots with NaN instead of
    # silently masking tokens by (possibly corrupted) counts.
    debug_checksum: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_DEBUG_CHECKSUM", "0") == "1"
    )
    # Per-core VMEM working-set budget (bytes) used to gate fused single
    # -kernel engines (ag_gemm, gemm_rs) vs the streaming XLA ring paths.
    fused_vmem_budget: int = field(
        default_factory=lambda: int(
            float(os.environ.get("TDTPU_FUSED_VMEM_BUDGET", str(96 * 1024 * 1024)))
        )
    )
    # Run the fused MoE decode TRANSPORT (chunked window DMAs + LL
    # state) off-TPU on the interpreter instead of demoting decode to
    # the XLA a2a (Transformer._moe_ep_ctx's off-TPU default, kept
    # because per-step interpreted dispatch can wedge the io_callback
    # worker pool on small hosts). Turn on for BOUNDED runs — the
    # multi-device execution evidence for the composed fused-LL decode
    # step (VERDICT r4 #4): tests/test_models.py and the dryrun run 3
    # consecutive steps under it. Expert GEMMs stay on the XLA path
    # off-TPU (Mosaic-only kernels still require real lowering).
    force_fused_transport: bool = field(
        default_factory=lambda: os.environ.get(
            "TDTPU_FORCE_FUSED_TRANSPORT", "0"
        ) == "1"
    )


config = Config()


def fused_vmem_budget() -> int:
    return config.fused_vmem_budget


_FLEET_SEED: int | None = None


def set_fleet_seed(seed: int | None) -> None:
    """Install (or clear, with ``None``) the fleet routing seed.

    Every routing/spill/affinity tie-break in
    :mod:`~triton_distributed_tpu.serving.fleet` hashes through this
    seed, and like the :class:`~triton_distributed_tpu.runtime.faults.
    FaultPlan` identity it is folded into :func:`interp_key` so cached
    kernel builds cannot leak across fleets routed differently."""
    global _FLEET_SEED
    _FLEET_SEED = seed


def fleet_seed() -> int | None:
    """The active fleet routing seed (None outside a fleet)."""
    return _FLEET_SEED


def interp_key() -> tuple:
    """Hashable key of the config state captured at pallas BUILD time
    (chaos delays are traced in; detect_races is baked into the
    interpreter params; force_compile flips interpret→Mosaic) —
    lru-cached kernel builders must include it so toggling any knob
    rebuilds instead of reusing a stale build.

    Includes the fault-engine trace key (runtime.faults.trace_key):
    the active :class:`~triton_distributed_tpu.runtime.faults.FaultPlan`
    identity and the collective-watchdog armed flag — both are traced
    into kernels (seeded delay/corruption ops; heartbeat callbacks), so
    activating/changing/clearing either must invalidate cached builds.
    The fleet routing seed (:func:`set_fleet_seed`) rides along for the
    same reason.
    """
    from triton_distributed_tpu.runtime import faults

    return (
        config.chaos_delay, config.detect_races, config.force_compile,
        config.debug_checksum, _FLEET_SEED,
    ) + faults.trace_key()


def autotune_enabled() -> bool:
    """Should ``method=None`` op entries consult the measured autotuner
    (vs the static heuristics)? Default: on real hardware yes, on the CPU
    interpreter no (benching simulated kernels is meaningless and slow).
    Override with TDTPU_AUTOTUNE=1/0.
    """
    env = os.environ.get("TDTPU_AUTOTUNE")
    if env is not None:
        return env == "1"
    return on_tpu()


def _use_interpret(force: bool | None) -> bool:
    """Shared should-we-interpret policy: forced, or running off-TPU.
    ``config.force_compile`` overrides the off-TPU default (AOT lowering
    against an unattached TPU topology needs real Mosaic)."""
    if force is not None:
        return bool(force)
    if config.force_interpret:
        # the explicit debugging knob wins over force_compile: someone
        # asking for the interpreter (race detector, chaos) must get it
        return True
    if config.force_compile:
        return False
    return not on_tpu()


def local_interpret(force: bool | None = None):
    """Pallas ``interpret=`` argument for kernels with NO cross-device ops.

    Off-TPU these run under the *plain* Pallas interpreter (True), not the
    TPU state machine: the simulation's io_callback threads starve XLA's
    CPU thread pool on small hosts (observed as a flaky deadlock with 8
    virtual devices on 1 core), and a kernel without remote DMA/semaphores
    gains nothing from the heavyweight simulation.
    """
    return _use_interpret(force)


_io_callback_patched = False
_pipeline_shim_applied = False
_compat_applied = False


def has_tpu_interpreter() -> bool:
    """Does this jax ship the TPU-simulation interpreter
    (``pltpu.InterpretParams`` — faithful remote-DMA + semaphore
    semantics on a CPU mesh)? Older jax lacks it entirely; the
    test-suite's Pallas-collective coverage requires it, and the
    graceful-degradation layer (ops falling back to XLA-native paths)
    is what keeps the package usable without it."""
    from jax.experimental.pallas import tpu as pltpu

    return hasattr(pltpu, "InterpretParams")


def pallas_collectives_available() -> bool:
    """Can Pallas collective kernels (remote DMA + semaphores) run in
    this process? True on real TPU and under ``force_compile`` (AOT
    lowering); off-TPU they need the TPU-simulation interpreter. When
    False, auto-selected engines degrade to their XLA-native
    equivalents (explicitly pinned Pallas engines still fail loudly —
    a pinned method is a contract, not a preference)."""
    if config.force_compile or on_tpu():
        return True
    return has_tpu_interpreter()


def ensure_compat():
    """Best-effort shims for jax API drift (graceful degradation, not
    emulation): the package targets current jax names; on an older jax
    the *renamed or superseded* APIs are aliased so that everything
    which does not require genuinely missing machinery keeps working,
    and the missing machinery degrades loudly-but-usably:

    * ``jax.shard_map`` ← ``jax.experimental.shard_map.shard_map``
      (``check_vma`` mapped to the old ``check_rep``).
    * ``pltpu.CompilerParams`` ← ``pltpu.TPUCompilerParams`` (unknown
      fields dropped — e.g. ``has_side_effects`` predates the rename).
    * ``pl.delay`` → no-op when the primitive is absent (chaos delays
      degrade to nothing; the fault engine's *structural* faults —
      stalls, signal drops, corruption — do not depend on it).
    * ``pltpu.reset_tpu_interpret_mode_state`` → no-op (no global
      interpreter state exists to reset).
    * ``jax.export`` imported so attribute access works (older jax has
      the submodule but does not auto-import it).

    Idempotent; opt out with ``TDTPU_NO_COMPAT_SHIMS=1``. The one thing
    NOT shimmed is the TPU-simulation interpreter itself (see
    :func:`has_tpu_interpreter`): faking remote-DMA semantics would be
    dishonest — callers must degrade to XLA-native paths instead.
    """
    global _compat_applied
    if _compat_applied or os.environ.get("TDTPU_NO_COMPAT_SHIMS") == "1":
        return
    _compat_applied = True
    import dataclasses
    import functools

    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
            if check_vma is not None:
                kw.setdefault("check_rep", check_vma)
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
            )

        jax.shard_map = shard_map
    if not hasattr(pltpu, "CompilerParams"):
        legacy = pltpu.TPUCompilerParams
        fields = {f.name for f in dataclasses.fields(legacy)}

        def CompilerParams(**kw):
            return legacy(**{k: v for k, v in kw.items() if k in fields})

        pltpu.CompilerParams = CompilerParams
    if not hasattr(jax, "export"):
        # the submodule exists but is not auto-imported (and package
        # __getattr__ raises) on older jax — importing it binds the attr
        try:
            from jax import export  # noqa: F401
        except ImportError:         # pragma: no cover — genuinely absent
            pass
    if not hasattr(pl, "delay"):
        pl.delay = lambda cycles: None
    if not hasattr(pltpu, "reset_tpu_interpret_mode_state"):
        pltpu.reset_tpu_interpret_mode_state = lambda: None
    if not hasattr(jax.lax, "axis_size"):
        # psum of a literal folds statically to the axis size — the
        # pre-axis_size idiom, so callers still get a Python int
        jax.lax.axis_size = lambda axis: jax.lax.psum(1, axis)


def ensure_pipeline_shim():
    """Make ``pltpu.emit_pipeline`` traceable off-TPU.

    The pipeline helper's ragged-edge DMA tiling asks the *runtime* for the
    TPU generation (jax._src.pallas.mosaic.pipeline._get_tpu_generation) at
    trace time, which raises on the CPU interpreter mesh. The generation
    only picks the second-minor tile multiple used to round up ragged tail
    blocks — our streaming kernels use even blockings and the interpreter
    ignores tiling entirely, so answering a fixed modern generation is
    semantically inert here.

    Guarded: applied only off-TPU, only when the private helper still has
    the expected zero-arg shape; if jax internals drift, raises a clear
    error instead of silently patching (set TDTPU_NO_INTERPRETER_SHIMS=1
    to skip the shim and run without emit_pipeline-based kernels).
    """
    global _pipeline_shim_applied
    if _pipeline_shim_applied or on_tpu():
        return
    if os.environ.get("TDTPU_NO_INTERPRETER_SHIMS") == "1":
        return
    import inspect

    try:
        import jax._src.pallas.mosaic.pipeline as _pipe

        fn = _pipe._get_tpu_generation
        if len(inspect.signature(fn).parameters) != 0:
            raise AttributeError("unexpected _get_tpu_generation signature")
    except (AttributeError, ImportError) as e:
        if not has_tpu_interpreter():
            # pre-interpreter jax: the pipeline helper this shim patches
            # does not exist either — nothing to do (collective kernels
            # degrade to XLA-native paths elsewhere)
            _pipeline_shim_applied = True
            return
        raise RuntimeError(
            "triton_distributed_tpu interpreter shim: jax internals have "
            "drifted (jax._src.pallas.mosaic.pipeline._get_tpu_generation "
            f"not patchable: {e}). Pin jax to a tested version or set "
            "TDTPU_NO_INTERPRETER_SHIMS=1."
        ) from e
    _pipe._get_tpu_generation = lambda: 5
    _pipeline_shim_applied = True


def ensure_interpreter_unblocked():
    """Unblock the TPU-simulation interpreter on small hosts.

    jax's ``io_callback_impl`` device_puts callback args onto cpu:0 and the
    interpreter's callbacks then force that pending cross-device copy
    (``np.array(val)`` in ``_allocate_buffer``). When every client thread is
    already parked inside a device's blocked callback — guaranteed here,
    where N virtual devices rendezvous through DMA waits on a 1-core host —
    the copy can never be scheduled and the process deadlocks (observed
    deterministically for buffers over ~128 KB/device). The interpreter's
    callback args are always materialized host buffers, so converting them
    in place with ``np.asarray`` needs no client thread at all.

    Process-wide (affects all jax io_callbacks); applied only off-TPU,
    opt-out via TDTPU_NO_IO_CALLBACK_PATCH=1.
    """
    global _io_callback_patched
    if _io_callback_patched or on_tpu():
        return
    if os.environ.get("TDTPU_NO_IO_CALLBACK_PATCH") == "1":
        return
    import inspect
    import logging

    import numpy as np
    import jax._src.callback as _cb
    from jax import tree_util
    from jax._src import config as _jax_config
    from jax._src import xla_bridge as _xb

    try:
        expected = {"result_avals", "callback", "sharding", "ordered"}
        params = inspect.signature(_cb.io_callback_impl).parameters
        if not expected.issubset(params) or not hasattr(_cb, "io_callback_p"):
            raise AttributeError(f"io_callback_impl params {set(params)}")
    except AttributeError as e:
        if not has_tpu_interpreter():
            # pre-interpreter jax: the deadlock this patch prevents is an
            # interpreter-only failure mode — skip quietly
            _io_callback_patched = True
            return
        raise RuntimeError(
            "triton_distributed_tpu interpreter shim: jax internals have "
            f"drifted (jax._src.callback.io_callback_impl not patchable: {e})."
            " Pin jax to a tested version or set TDTPU_NO_IO_CALLBACK_PATCH=1"
            " (large interpreted kernels may then deadlock on small hosts)."
        ) from e

    logger = logging.getLogger("jax._src.callback")

    def io_callback_impl(*args, result_avals, callback, sharding, ordered):
        # Same contract as the original impl, minus the device_put of args
        # onto cpu:0 (the deadlock); callbacks still run under a cpu
        # default_device and failures are still logged.
        del result_avals, sharding, ordered
        args = tuple(np.asarray(a) for a in args)
        cpu_device, *_ = _xb.local_devices(backend="cpu")
        with _jax_config.default_device(cpu_device):
            try:
                return tree_util.tree_map(np.asarray, callback(*args))
            except BaseException:
                logger.exception("jax.io_callback failed")
                raise

    _cb.io_callback_impl = io_callback_impl
    _cb.io_callback_p.def_impl(io_callback_impl)
    _io_callback_patched = True


try:
    ensure_compat()
except Exception:                               # pragma: no cover
    # a failed shim must never break package import; the APIs it would
    # have aliased will then fail at their call sites with jax's own
    # (clear) AttributeErrors
    import logging

    logging.getLogger(__name__).exception("ensure_compat failed")


def interpret_params(force: bool | None = None):
    """Pallas ``interpret=`` argument for the current platform.

    On TPU hardware: ``False`` (compile with Mosaic). Anywhere else (the
    8-virtual-device CPU mesh the tests run on): ``InterpretParams`` so that
    remote DMA + semaphore semantics are simulated faithfully.
    """
    from jax.experimental.pallas import tpu as pltpu

    if not _use_interpret(force):
        if not on_tpu():
            # force_compile from a CPU-backed process (AOT lowering for a
            # TPU topology): emit_pipeline still asks the *runtime* for
            # the TPU generation at trace time — answer for the target
            ensure_pipeline_shim()
        return False
    ensure_interpreter_unblocked()
    ensure_pipeline_shim()
    if not has_tpu_interpreter():
        # jax without the TPU-simulation interpreter: degrade to the
        # plain Pallas interpreter. Purely local kernels still run;
        # kernels that need remote DMA / semaphore semantics fail loudly
        # at trace time — callers should have demoted to XLA-native
        # engines first (ops.overlap.with_fallback / method fallbacks).
        return True
    return pltpu.InterpretParams(
        detect_races=config.detect_races,
        dma_execution_mode="on_wait",
    )
