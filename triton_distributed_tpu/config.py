"""Global configuration and platform detection.

The reference framework configures itself through env vars
(``NVSHMEM_*``, ``USE_TRITON_DISTRIBUTED_AOT``; reference:
python/triton_dist/layers/nvidia/sp_flash_decode_layer.py:32-39). Here the
switches that matter are: which backend are we on (TPU vs CPU-simulated
mesh), whether Pallas kernels should run under the TPU interpreter (the
CPU path used by the test-suite), and test-only chaos/race knobs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def backend() -> str:
    import jax

    return jax.default_backend()


def on_tpu() -> bool:
    return backend() == "tpu"


@dataclass
class Config:
    # Force Pallas interpreter mode even on TPU (debugging).
    force_interpret: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_FORCE_INTERPRET", "0") == "1"
    )
    # Enable the interpreter's DMA race detector (CPU test runs only).
    # TPU-native answer to the reference's chaos-delay substitute for a race
    # detector (reference: python/triton_dist/kernels/nvidia/allgather.py:72-77).
    detect_races: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_DETECT_RACES", "0") == "1"
    )
    # Inject randomized delays into comm paths to widen race windows
    # ("for_correctness" testing in the reference).
    chaos_delay: bool = field(
        default_factory=lambda: os.environ.get("TDTPU_CHAOS_DELAY", "0") == "1"
    )
    # Default symmetric workspace budget (bytes) for contexts that
    # pre-allocate communication buffers (reference NVSHMEM_SYMMETRIC_SIZE,
    # launch.sh:1-41).
    symmetric_size: int = field(
        default_factory=lambda: int(
            float(os.environ.get("TDTPU_SYMMETRIC_SIZE", "1e9"))
        )
    )


    # Per-core VMEM working-set budget (bytes) used to gate fused single
    # -kernel engines (ag_gemm, gemm_rs) vs the streaming XLA ring paths.
    fused_vmem_budget: int = field(
        default_factory=lambda: int(
            float(os.environ.get("TDTPU_FUSED_VMEM_BUDGET", str(96 * 1024 * 1024)))
        )
    )


config = Config()


def fused_vmem_budget() -> int:
    return config.fused_vmem_budget


def _use_interpret(force: bool | None) -> bool:
    """Shared should-we-interpret policy: forced, or running off-TPU."""
    if force is not None:
        return bool(force)
    return config.force_interpret or not on_tpu()


def local_interpret(force: bool | None = None):
    """Pallas ``interpret=`` argument for kernels with NO cross-device ops.

    Off-TPU these run under the *plain* Pallas interpreter (True), not the
    TPU state machine: the simulation's io_callback threads starve XLA's
    CPU thread pool on small hosts (observed as a flaky deadlock with 8
    virtual devices on 1 core), and a kernel without remote DMA/semaphores
    gains nothing from the heavyweight simulation.
    """
    return _use_interpret(force)


def interpret_params(force: bool | None = None):
    """Pallas ``interpret=`` argument for the current platform.

    On TPU hardware: ``False`` (compile with Mosaic). Anywhere else (the
    8-virtual-device CPU mesh the tests run on): ``InterpretParams`` so that
    remote DMA + semaphore semantics are simulated faithfully.
    """
    from jax.experimental.pallas import tpu as pltpu

    if not _use_interpret(force):
        return False
    return pltpu.InterpretParams(
        detect_races=config.detect_races,
        dma_execution_mode="on_wait",
    )
