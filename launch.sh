#!/usr/bin/env bash
# Multi-host launcher for triton_distributed_tpu programs.
#
# ≡ reference launch.sh (torchrun + NVSHMEM env, launch.sh:1-41): one
# process per host, rendezvous via env vars that
# runtime.initialize_distributed() consumes (jax.distributed bootstrap
# replaces the NCCL process group + NVSHMEM uniqueid broadcast).
#
# Usage:
#   Single host (real chips or dev CPU mesh):
#     bash launch.sh python tutorials/06-ag-gemm.py
#     TDTPU_LOCAL_DEVICES=8 bash launch.sh python my_script.py   # CPU mesh
#
#   Multi-host (run on EVERY host, e.g. via `gcloud compute tpus tpu-vm
#   ssh --worker=all --command=...`; on Cloud TPU pods the three vars
#   are auto-detected by jax and may be omitted):
#     JAX_COORDINATOR_ADDRESS=host0:8476 \
#     JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=$(hostname_index) \
#     bash launch.sh python train.py
set -euo pipefail

# Dev convenience: a virtual CPU mesh of N devices (the test-harness env).
if [[ -n "${TDTPU_LOCAL_DEVICES:-}" ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=${TDTPU_LOCAL_DEVICES}"
  export JAX_PLATFORMS=cpu
fi

export JAX_TRACEBACK_FILTERING="${JAX_TRACEBACK_FILTERING:-auto}"

# Quiet the usual noise, mirroring NCCL_DEBUG=ERROR in the reference.
export TPU_STDERR_LOG_LEVEL="${TPU_STDERR_LOG_LEVEL:-3}"
export TF_CPP_MIN_LOG_LEVEL="${TF_CPP_MIN_LOG_LEVEL:-2}"

if [[ $# -eq 0 ]]; then
  echo "usage: launch.sh <command...>   (e.g. launch.sh python train.py)" >&2
  exit 64
fi

exec "$@"
