// Native runtime utilities for triton_distributed_tpu.
//
// The reference keeps its host-side hot paths native: moe_utils.cu
// (csrc/lib/moe_utils.cu:61-356, token sort/pad for grouped GEMM),
// the AOT runtime (tools/runtime/triton_aot_runtime.cc:26-61, artifact
// loading outside any framework), and pybind glue (csrc/op_pybind.cc).
// This file is the TPU-native equivalent set, exposed as a plain C ABI
// (loaded via ctypes — no pybind11 in this toolchain):
//
//   * artifact store  — atomic write + FNV-1a-checksummed mmap read for
//                       serialized XLA executables (tools/aot.py).
//   * moe align       — host-side moe_align_block_size for CPU-side
//                       preprocessing (dataloaders / request routers),
//                       same layout contract as kernels/moe_utils.py.
//   * token dataset   — mmap'd uint32 token file with seeded random
//                       batch sampling: the IO path of the training
//                       loop, zero-copy until the final pack.
//
// Build: g++ -O3 -shared -fPIC -o libtdtpu_native.so tdtpu_native.cpp
// (driven by tools/native.py, cached under csrc/build/).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

extern "C" {

// ------------------------------------------------------------------ artifact

static const uint64_t kMagic = 0x5452415550544454ULL;  // "TDTPUART"

static uint64_t fnv1a(const uint8_t* p, uint64_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (uint64_t i = 0; i < n; ++i) {
    h ^= p[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Atomic checksummed write: tmp file + rename. Returns 0 on success.
int tdtpu_artifact_write(const char* path, const uint8_t* buf, uint64_t len) {
  std::vector<char> tmp(strlen(path) + 8);
  snprintf(tmp.data(), tmp.size(), "%s.tmp", path);
  FILE* f = fopen(tmp.data(), "wb");
  if (!f) return -1;
  uint64_t h = fnv1a(buf, len);
  int ok = fwrite(&kMagic, 8, 1, f) == 1 && fwrite(&len, 8, 1, f) == 1 &&
           (len == 0 || fwrite(buf, 1, len, f) == len) &&
           fwrite(&h, 8, 1, f) == 1;
  ok = fclose(f) == 0 && ok;
  if (!ok) { remove(tmp.data()); return -2; }
  if (rename(tmp.data(), path) != 0) { remove(tmp.data()); return -3; }
  return 0;
}

// Returns payload size, or <0 on error (-2: bad magic, -3: bad checksum).
int64_t tdtpu_artifact_size(const char* path) {
  struct stat st;
  if (stat(path, &st) != 0 || st.st_size < 24) return -1;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0, len = 0;
  if (fread(&magic, 8, 1, f) != 1 || fread(&len, 8, 1, f) != 1 ||
      magic != kMagic || (uint64_t)st.st_size != 24 + len) {
    fclose(f);
    return -2;
  }
  fclose(f);
  return (int64_t)len;
}

// mmap + verify + copy into caller buffer. Returns 0 on success.
int tdtpu_artifact_read(const char* path, uint8_t* out, uint64_t len) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  uint64_t total = 24 + len;
  void* m = mmap(nullptr, total, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (m == MAP_FAILED) return -1;
  const uint8_t* base = (const uint8_t*)m;
  uint64_t stored_h;
  memcpy(&stored_h, base + 16 + len, 8);
  int rc = 0;
  if (fnv1a(base + 16, len) != stored_h) {
    rc = -3;
  } else {
    memcpy(out, base + 16, len);
  }
  munmap(m, total);
  return rc;
}

// ----------------------------------------------------------------- moe align

// Host-side moe_align_block_size: sort (token,slot) pairs by expert,
// pad each expert segment to block_m. Layout identical to
// kernels/moe_utils.moe_align_block_size (sentinel = total).
// sorted_token_ids: capacity entries; block_expert: capacity/block_m;
// splits: num_experts. Returns used capacity, or <0 on error.
int64_t tdtpu_moe_align_block_size(
    const int32_t* topk_ids, int64_t m, int64_t k, int64_t num_experts,
    int64_t block_m, int32_t* sorted_token_ids, int32_t* block_expert,
    int32_t* splits, int64_t capacity) {
  int64_t total = m * k;
  std::vector<int64_t> count(num_experts, 0);
  for (int64_t i = 0; i < total; ++i) {
    int32_t e = topk_ids[i];
    if (e < 0 || e >= num_experts) return -1;
    count[e]++;
  }
  std::vector<int64_t> padded_off(num_experts + 1, 0);
  for (int64_t e = 0; e < num_experts; ++e) {
    splits[e] = (int32_t)count[e];
    int64_t padded = (count[e] + block_m - 1) / block_m * block_m;
    padded_off[e + 1] = padded_off[e] + padded;
  }
  int64_t used = padded_off[num_experts];
  if (used > capacity) return -2;
  for (int64_t i = 0; i < capacity; ++i) sorted_token_ids[i] = (int32_t)total;
  std::vector<int64_t> cursor(padded_off.begin(), padded_off.end() - 1);
  for (int64_t i = 0; i < total; ++i) {        // stable: ascending i
    int32_t e = topk_ids[i];
    sorted_token_ids[cursor[e]++] = (int32_t)i;
  }
  int64_t nblocks = capacity / block_m;
  for (int64_t b = 0; b < nblocks; ++b) {
    int64_t start = b * block_m;
    int64_t e = (int64_t)(std::upper_bound(padded_off.begin() + 1,
                                           padded_off.end(), start) -
                          (padded_off.begin() + 1));
    block_expert[b] = (int32_t)std::min<int64_t>(e, num_experts - 1);
  }
  return used;
}

// -------------------------------------------------------------- token dataset

struct TdtpuDataset {
  uint32_t* data;
  uint64_t n_tokens;
  uint64_t map_len;
};

// Opens an mmap'd file of little-endian uint32 tokens. Returns handle
// (opaque pointer) or null.
void* tdtpu_dataset_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 4) { close(fd); return nullptr; }
  void* m = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  close(fd);
  if (m == MAP_FAILED) return nullptr;
  auto* ds = new TdtpuDataset{(uint32_t*)m, (uint64_t)st.st_size / 4,
                              (uint64_t)st.st_size};
  return ds;
}

uint64_t tdtpu_dataset_len(void* handle) {
  return ((TdtpuDataset*)handle)->n_tokens;
}

// splitmix64 — deterministic cross-platform sampling.
static uint64_t splitmix64(uint64_t& s) {
  uint64_t z = (s += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Fill (batch, seqlen+1) with random contiguous windows — inputs and
// shifted targets come from one window. Returns 0, or -1 if the file
// is shorter than one window.
int tdtpu_dataset_sample(void* handle, uint64_t seed, int64_t batch,
                         int64_t seqlen, uint32_t* out) {
  auto* ds = (TdtpuDataset*)handle;
  int64_t window = seqlen + 1;
  if (ds->n_tokens < (uint64_t)window) return -1;
  uint64_t range = ds->n_tokens - window + 1;
  uint64_t s = seed;
  for (int64_t b = 0; b < batch; ++b) {
    uint64_t off = splitmix64(s) % range;
    memcpy(out + b * window, ds->data + off, window * 4);
  }
  return 0;
}

void tdtpu_dataset_close(void* handle) {
  auto* ds = (TdtpuDataset*)handle;
  munmap(ds->data, ds->map_len);
  delete ds;
}

}  // extern "C"
